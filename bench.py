"""Headline benchmark: Llama-class decoder training throughput on one chip.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N, "unit": "tokens/s",
   "vs_baseline": R}

North-star metric per BASELINE.md ("Train tokens/sec/chip at 7B Llama-class");
on this single v5e-lite chip the model is scaled to fit HBM, and we also
report model FLOPs utilization so the number transfers across model sizes.
vs_baseline: the reference repo publishes no tokens/sec numbers in-repo
(BASELINE.md), so the ratio is against the recorded value of our own first
round once BENCH_r1.json exists; until then 1.0.

Capture strategy (round-3 hardening): the parent process runs the TPU
measurement in a CHILD process with a hard deadline — backend init on a
wedged device pool can hang for minutes (observed rounds 1-3), and a failed
in-process init is cached by jax. If the TPU child fails or times out, a CPU
child still records a number, with the TPU failure reason + stderr tail and
the last-known-good on-hardware result (cached across invocations) in
detail so the artifact is diagnosable.
"""

from __future__ import annotations

import functools
import json
import math
import os
import subprocess
import sys
import time

_LKG_PATH = "/tmp/ray_tpu_bench_last_good.json"
_BUDGET_S = float(os.environ.get("RAY_TPU_BENCH_BUDGET_S", "540"))


def _measure(platform: str) -> dict:
    """Run the train-step measurement on the CURRENT jax platform."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import llama_config, transformer

    on_tpu = jax.default_backend() == "tpu"
    # round-4 lever, measured on hardware 2026-07-31: no-remat (with or
    # without int8 optimizer state) either OOMs or crashes this infra's
    # remote-compile helper, and int8 state alone costs 16% in
    # quantize/dequantize bandwidth (benchmarks/train_sweep.py int8_*).
    # The lever that LANDED is batch 16: 27.4k -> 28.1k tok/s. Keep the
    # no-remat attempt opt-in for when the infra envelope grows.
    attempt_no_remat = on_tpu and os.environ.get(
        "RAY_TPU_BENCH_NO_REMAT", "0") == "1"
    if on_tpu:
        # config picked by on-hardware sweeps (rounds 2-4,
        # benchmarks/train_sweep.py): wide beats deep on the MXU, the
        # Pallas flash kernels (fwd+bwd) cut the step 31% at s2048, and
        # batch 16 is the largest that this infra's compile helper accepts
        cfg = llama_config(
            "tiny", vocab_size=32000, max_seq_len=2048, d_model=2048,
            n_layers=8, n_heads=16, n_kv_heads=8, d_ff=8192, dtype=jnp.bfloat16,
            remat=not attempt_no_remat,
        )
        batch, seq, steps = 16, 2048, 20
    else:  # CPU smoke sizing
        cfg = llama_config("tiny", vocab_size=512, max_seq_len=256, dtype=jnp.float32)
        batch, seq, steps = 2, 128, 3

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(math.prod(p.shape) for p in jax.tree.leaves(params))
    if attempt_no_remat:
        from ray_tpu.train.optim import adamw_int8

        opt = adamw_int8(1e-4, weight_decay=0.01)
    else:
        opt = optax.adamw(1e-4, weight_decay=0.01)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(params, tokens, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32))

    # warmup / compile. NOTE: hard-sync with float(loss) — block_until_ready
    # is a no-op on the axon remote platform and under-reports step time.
    params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)  # chain of donated params forces sequential execution
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    # 6ND approximation for train FLOPs (fwd+bwd), attention excluded
    flops_per_token = 6 * n_params
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_token / peak
    # secondary MFU including causal self-attention matmul FLOPs
    # (6·L·S·d_attn per token: 2 matmuls × 2·(S/2)·H·Dh fwd, ×3 for train)
    attn_flops_per_token = 6 * cfg.n_layers * seq * cfg.n_heads * cfg.head_dim
    mfu_attn = tokens_per_sec * (flops_per_token + attn_flops_per_token) / peak
    return {
        "tokens_per_sec": tokens_per_sec,
        "model_params": n_params,
        "batch": batch, "seq": seq,
        "step_ms": round(dt * 1e3, 2),
        "mfu_6nd": round(mfu, 4),
        "mfu_incl_attn": round(mfu_attn, 4),
        "final_loss": round(float(loss), 3),
        "backend": jax.default_backend(),
    }


def _child_main(platform: str) -> int:
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    elif platform == "tpu":
        # SELF-terminating init deadline (tpu_probe.py pattern): backend
        # init on a wedged pool hangs indefinitely, and the parent must
        # NEVER kill this process from outside — SIGKILL mid-grant is what
        # wedges the pool for everyone (PERF.md post-mortems, rounds 1-4).
        # The default SIGALRM disposition exits at the C level even while
        # blocked inside native init; the alarm is cleared the moment the
        # backend answers, after which measurement time is bounded.
        import signal

        signal.alarm(int(float(os.environ.get(
            "RAY_TPU_BENCH_INIT_BUDGET_S", "240"))))
        import jax

        if jax.default_backend() == "tpu":
            import jax.numpy as jnp

            (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
        signal.alarm(0)
    fallback_err = None
    try:
        out = _measure(platform)
    except Exception as e:
        retry = False
        if (platform == "tpu"
                and os.environ.get("RAY_TPU_BENCH_NO_REMAT", "0") == "1"):
            try:
                import jax

                # only retry when the no-remat config actually RAN on the
                # tpu backend — a backend-init failure would just repeat
                # the identical error and burn the child's budget
                retry = jax.default_backend() == "tpu"
            except Exception:
                retry = False
        if not retry:
            raise
        fallback_err = f"{type(e).__name__}: {e}"[:200]
        out = None
    if out is None:
        # the no-remat config didn't fit/compile: fall back to the proven
        # remat config in the SAME child. This runs AFTER the except block
        # on purpose — while the except clause is live, the interpreter's
        # exception state still references the traceback whose frames pin
        # the failed attempt's params/opt_state device buffers, and no
        # amount of gc inside the clause can free them.
        import gc

        gc.collect()
        import jax

        jax.clear_caches()
        os.environ["RAY_TPU_BENCH_NO_REMAT"] = "0"
        out = _measure(platform)
        out["no_remat_fallback"] = fallback_err
    print("@@RESULT@@" + json.dumps(out))
    return 0


def _run_child(platform: str, timeout: float) -> tuple[dict | None, str]:
    env = dict(os.environ)
    env["RAY_TPU_BENCH_CHILD"] = platform
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # a wedged device pool blocks even `import jax` while the relay
        # env var is present — the CPU fallback must not dial it
        env.pop("PALLAS_AXON_POOL_IPS", None)
    else:
        env["RAY_TPU_BENCH_INIT_BUDGET_S"] = str(max(60.0, timeout - 30.0))
    try:
        if platform == "tpu":
            # the TPU child self-terminates via its init alarm; the parent
            # only STOPS WAITING on deadline — it must never SIGKILL a
            # process that may hold a half-complete device-pool grant
            # (killing mid-grant wedges the pool: rounds 1-4 post-mortems)
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
            try:
                stdout, stderr = proc.communicate(timeout=timeout + 60.0)
            except subprocess.TimeoutExpired:
                return None, (f"{platform} child unresponsive past "
                              f"{timeout + 60:.0f}s; abandoned un-killed "
                              "(its init alarm will exit it)")
            r = subprocess.CompletedProcess(proc.args, proc.returncode,
                                            stdout, stderr)
        else:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                capture_output=True, text=True, timeout=timeout,
                env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return None, f"{platform} child exceeded {timeout:.0f}s"
    for line in (r.stdout or "").splitlines():
        if line.startswith("@@RESULT@@"):
            res = json.loads(line[len("@@RESULT@@"):])
            if platform == "tpu" and res.get("backend") != "tpu":
                return None, f"child ran on {res.get('backend')!r}, not tpu"
            return res, ""
    tail = "\n".join((r.stderr or "").strip().splitlines()[-4:])[-600:]
    return None, f"{platform} child rc={r.returncode}: {tail}"


def main():
    child = os.environ.get("RAY_TPU_BENCH_CHILD")
    if child:
        return _child_main(child)

    t0 = time.monotonic()
    diag: dict = {}
    result = None
    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        result, err = _run_child("tpu", timeout=max(60.0, _BUDGET_S - 100.0))
        if result is None:
            diag["tpu_unavailable"] = err
    else:
        diag["tpu_unavailable"] = "JAX_PLATFORMS=cpu preset"

    if result is not None:
        # cache last-known-good for diagnosability of future wedged runs
        try:
            with open(_LKG_PATH, "w") as f:
                json.dump({**result, "ts": time.time()}, f)
        except OSError:
            pass
    else:
        remaining = max(30.0, _BUDGET_S - (time.monotonic() - t0) - 10.0)
        result, err = _run_child("cpu", timeout=remaining)
        if result is None:
            # last resort: measure CPU in-process so SOMETHING is recorded
            diag["cpu_child_failed"] = err
            os.environ["JAX_PLATFORMS"] = "cpu"
            result = _measure("cpu")
        try:
            lkg = json.load(open(_LKG_PATH))
            diag["last_known_good_tpu"] = {
                "tokens_per_sec": round(lkg.get("tokens_per_sec", 0), 1),
                "mfu_6nd": lkg.get("mfu_6nd"),
                "age_s": round(time.time() - lkg.get("ts", 0.0), 0)}
        except Exception:
            pass

    tokens_per_sec = result.pop("tokens_per_sec")

    # baseline = the earliest recorded round (docstring contract)
    rounds = []
    here = os.path.dirname(os.path.abspath(__file__)) or "."
    for f in os.listdir(here):
        if f.startswith("BENCH_r") and f.endswith(".json"):
            try:
                n = int(f[len("BENCH_r"):-len(".json")])
                rec = json.load(open(os.path.join(here, f)))
                if rec.get("metric") == "train_tokens_per_sec_per_chip":
                    rounds.append((n, rec["value"]))
            except Exception:
                pass
    prior = min(rounds)[1] if rounds else None
    vs = round(tokens_per_sec / prior, 3) if prior else 1.0

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": vs,
        "detail": {**result, **diag},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
