"""Headline benchmark: Llama-class decoder training throughput on one chip.

Prints ONE JSON line:
  {"metric": "train_tokens_per_sec_per_chip", "value": N, "unit": "tokens/s",
   "vs_baseline": R}

North-star metric per BASELINE.md ("Train tokens/sec/chip at 7B Llama-class");
on this single v5e-lite chip the model is scaled to fit HBM, and we also
report model FLOPs utilization so the number transfers across model sizes.
vs_baseline: the reference repo publishes no tokens/sec numbers in-repo
(BASELINE.md), so the ratio is against the recorded value of our own first
round once BENCH_r1.json exists; until then 1.0.
"""

from __future__ import annotations

import functools
import json
import math
import os
import subprocess
import sys
import time

_TPU_PROBE_CODE = "import jax; d = jax.devices(); assert d; print(d[0].platform)"


def _probe_tpu(attempts: int = 2, timeout: float = 200.0) -> tuple[bool, str]:
    """Check in a SUBPROCESS that the TPU backend can initialize.

    Round-1 failure mode: a wedged device-pool grant made jax backend init
    raise Unavailable (or hang for minutes) — and a failed in-process init is
    cached by jax, so we probe out-of-process with a hard timeout and retry
    with backoff before committing this process to the TPU platform.
    """
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False, "JAX_PLATFORMS=cpu preset"
    err = ""
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _TPU_PROBE_CODE],
                capture_output=True, text=True, timeout=timeout)
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
                if plat not in ("cpu",):
                    return True, plat
                return False, f"probe found platform {plat!r}"
            err = (r.stderr or "").strip().splitlines()[-1:] or ["rc=%d" % r.returncode]
            err = err[0][-300:]
        except subprocess.TimeoutExpired:
            err = f"TPU backend init hung >{timeout:.0f}s"
        if i + 1 < attempts:
            # wedged device-pool grants (observed rounds 1-2) can take
            # minutes to clear — but the TOTAL probe budget must stay well
            # inside the driver's bench timeout so a wedged pool still
            # yields a recorded (CPU-fallback) number instead of rc=124
            time.sleep(20)
    return False, err


def main():
    tpu_ok, tpu_note = _probe_tpu()
    if not tpu_ok:
        # fall back to a CPU run so the artifact still records a number,
        # with the TPU failure reason in detail.
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    if not tpu_ok:
        jax.config.update("jax_platforms", "cpu")

    on_tpu = jax.default_backend() == "tpu"
    from ray_tpu.models import llama_config, transformer

    if on_tpu:
        # config picked by on-hardware sweep (round 2): wide beats deep on
        # MXU utilization — d_model 2048 nearly doubles MFU vs 1024
        # (0.37 vs 0.19) at 634M params, the largest shape that fits HBM
        # with AdamW state + remat
        cfg = llama_config(
            "tiny", vocab_size=32000, max_seq_len=2048, d_model=2048,
            n_layers=8, n_heads=16, n_kv_heads=8, d_ff=8192, dtype=jnp.bfloat16,
        )
        batch, seq, steps = 8, 2048, 30
    else:  # CPU smoke sizing
        cfg = llama_config("tiny", vocab_size=512, max_seq_len=256, dtype=jnp.float32)
        batch, seq, steps = 2, 128, 3

    params = transformer.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(math.prod(p.shape) for p in jax.tree.leaves(params))
    opt = optax.adamw(1e-4, weight_decay=0.01)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(params, tokens, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32))

    # warmup / compile. NOTE: hard-sync with float(loss) — block_until_ready
    # is a no-op on the axon remote platform and under-reports step time.
    params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    float(loss)  # chain of donated params forces sequential execution
    dt = (time.perf_counter() - t0) / steps

    tokens_per_sec = batch * seq / dt
    # 6ND approximation for train FLOPs (fwd+bwd), attention excluded
    flops_per_token = 6 * n_params
    peak = 197e12 if on_tpu else 1e12  # v5e bf16 peak
    mfu = tokens_per_sec * flops_per_token / peak

    # baseline = the earliest recorded round (docstring contract)
    rounds = []
    for f in os.listdir("."):
        if f.startswith("BENCH_r") and f.endswith(".json"):
            try:
                n = int(f[len("BENCH_r"):-len(".json")])
                rec = json.load(open(f))
                if rec.get("metric") == "train_tokens_per_sec_per_chip":
                    rounds.append((n, rec["value"]))
            except Exception:
                pass
    prior = min(rounds)[1] if rounds else None
    vs = round(tokens_per_sec / prior, 3) if prior else 1.0

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": vs,
        "detail": {
            "model_params": n_params,
            "batch": batch, "seq": seq,
            "step_ms": round(dt * 1e3, 2),
            "mfu_6nd": round(mfu, 4),
            "final_loss": round(float(loss), 3),
            "backend": jax.default_backend(),
            **({} if tpu_ok else {"tpu_unavailable": tpu_note}),
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
