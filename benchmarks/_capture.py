"""Shared TPU-safe capture harness for benchmark scripts.

One implementation of the pool-hygiene rules every bench must follow
(PERF.md post-mortems, rounds 1-4):

- the TPU measurement runs in a CHILD whose backend init is bounded by a
  SELF-terminating ``signal.alarm`` — never killed from outside, because
  SIGKILL/SIGTERM mid-grant is what wedges the shared device pool;
- the parent only STOPS WAITING on deadline (the child's alarm exits it);
- CPU children strip ``PALLAS_AXON_POOL_IPS`` so a wedged pool can't
  block even ``import jax``;
- a TPU child that lands on another backend exits immediately with a
  marker instead of burning the budget measuring the wrong platform;
- last-known-good TPU results are cached across invocations.

bench.py (the driver-run headline bench) keeps its own self-contained
copy on purpose — it must work standalone at the repo root.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_MARK = "@@RESULT@@"
_WRONG_BACKEND = "@@WRONG_BACKEND@@"


def child_guard(child_env: str, platform: str) -> None:
    """Call FIRST in the child: arm the init alarm, confirm the backend
    with one real device op, then disarm. Exits (rc 3) when a TPU child
    lands elsewhere so the parent can skip straight to the CPU child."""
    if platform != "tpu":
        return
    import signal

    signal.alarm(int(float(os.environ.get(child_env + "_INIT_BUDGET_S",
                                          "240"))))
    import jax

    if jax.default_backend() != "tpu":
        signal.alarm(0)
        print(_WRONG_BACKEND + jax.default_backend(), flush=True)
        os._exit(3)
    import jax.numpy as jnp

    (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    signal.alarm(0)


def emit(result: dict) -> None:
    print(_MARK + json.dumps(result))


def run_child(script_path: str, child_env: str, platform: str,
              timeout: float, cwd: str) -> tuple[dict | None, str]:
    env = dict(os.environ)
    env[child_env] = platform
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
    else:
        env[child_env + "_INIT_BUDGET_S"] = str(max(60.0, timeout - 30.0))
    try:
        if platform == "tpu":
            proc = subprocess.Popen(
                [sys.executable, script_path],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                env=env, cwd=cwd)
            try:
                stdout, stderr = proc.communicate(timeout=timeout + 60.0)
            except subprocess.TimeoutExpired:
                return None, (f"tpu child unresponsive past "
                              f"{timeout + 60:.0f}s; abandoned un-killed "
                              "(its init alarm will exit it)")
            rc = proc.returncode
        else:
            r = subprocess.run([sys.executable, script_path],
                               capture_output=True, text=True,
                               timeout=timeout, env=env, cwd=cwd)
            stdout, stderr, rc = r.stdout, r.stderr, r.returncode
    except subprocess.TimeoutExpired:
        return None, f"{platform} child exceeded {timeout:.0f}s"
    for line in (stdout or "").splitlines():
        if line.startswith(_WRONG_BACKEND):
            return None, (f"tpu backend unavailable (child landed on "
                          f"{line[len(_WRONG_BACKEND):]!r})")
        if line.startswith(_MARK):
            res = json.loads(line[len(_MARK):])
            if platform == "tpu" and res.get("backend") != "tpu":
                return None, f"child ran on {res.get('backend')!r}, not tpu"
            return res, ""
    tail = "\n".join((stderr or "").strip().splitlines()[-4:])[-600:]
    return None, f"{platform} child rc={rc}: {tail}"


def orchestrate(script_path: str, child_env: str, budget_s: float,
                lkg_path: str, lkg_fields: list[str],
                cwd: str) -> dict:
    """Parent flow: TPU child → LKG cache on success; else CPU child with
    the cached last-known-good TPU numbers attached for diagnosability."""
    t0 = time.monotonic()
    diag: dict = {}
    result = None
    if not os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        result, err = run_child(script_path, child_env, "tpu",
                                max(60.0, budget_s - 100.0), cwd)
        if result is None:
            diag["tpu_unavailable"] = err
    else:
        diag["tpu_unavailable"] = "JAX_PLATFORMS=cpu preset"

    if result is not None:
        try:
            with open(lkg_path, "w") as f:
                json.dump({**result, "ts": time.time()}, f)
        except OSError:
            pass
    else:
        remaining = max(60.0, budget_s - (time.monotonic() - t0) - 10.0)
        result, err = run_child(script_path, child_env, "cpu",
                                remaining, cwd)
        if result is None:
            diag["cpu_child_failed"] = err
            result = {"backend": "none"}
        try:
            lkg = json.load(open(lkg_path))
            diag["last_known_good_tpu"] = {
                **{k: lkg.get(k) for k in lkg_fields},
                "age_s": round(time.time() - lkg.get("ts", 0.0), 0)}
        except Exception:
            pass
    return {"ts": time.strftime("%Y-%m-%d %H:%M"), **result, **diag}
