"""Attention micro-bench on the real chip: flash (Pallas) vs reference (XLA)
fwd+bwd at the headline-bench shape, sweeping block sizes.

Usage: python benchmarks/attn_bench.py [T ...]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def timeit(f, *args, iters=20):
    out = f(*args)
    jax.tree.map(lambda x: x.block_until_ready(), out)
    # hard sync for remote platforms where block_until_ready is a no-op
    jax.tree.leaves(out)[0].addressable_data(0)
    float(jax.tree.leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    float(jax.tree.leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    from ray_tpu.ops.flash_attention import flash_attention, _reference_bhtd

    B, H, D = 8, 16, 128
    seqs = [int(a) for a in sys.argv[1:]] or [2048]
    print("backend:", jax.default_backend())
    for T in seqs:
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (B, H, T, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (B, H, T, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, H, T, D), jnp.bfloat16)

        def ref_loss(q, k, v):
            return _reference_bhtd(q, k, v, causal=True, scale=D**-0.5).astype(jnp.float32).sum()

        gref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))
        try:
            ms = timeit(gref, q, k, v)
            print(f"T={T} reference fwd+bwd: {ms:.2f} ms")
        except Exception as e:
            print(f"T={T} reference failed: {type(e).__name__}: {e}")

        for bq, bk in [(256, 256), (512, 512), (256, 512), (512, 256), (1024, 512)]:
            if T % bq or T % bk:
                continue

            def fl_loss(q, k, v, bq=bq, bk=bk):
                return flash_attention(q, k, v, True, None, bq, bk, False).astype(jnp.float32).sum()

            gfl = jax.jit(jax.grad(fl_loss, argnums=(0, 1, 2)))
            try:
                ms = timeit(gfl, q, k, v)
                print(f"T={T} flash bq={bq} bk={bk} fwd+bwd: {ms:.2f} ms")
            except Exception as e:
                print(f"T={T} flash bq={bq} bk={bk} failed: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
