"""One-shot on-chip artifact capture, in judged-priority order.

Runs, sequentially (one process owns the chip at a time, each harness
already hardened with self-terminating TPU children):

  0. benchmarks/collective_bench.py  -> MICROBENCH.json `collective_*`
                                        (host ring: fp32 vs int8_block
                                        bytes-on-wire + wall time; no chip
                                        needed, runs even on a wedged pool)
  1. bench.py                    -> BENCH (train tokens/s + MFU) + LKG
  2. benchmarks/llm_serving_bench.py -> LLM_BENCH.json (TTFT/decode/agg)
  3. benchmarks/llm_load_bench.py    -> LLM_BENCH.json `pd` section
                                        (arrival sweep + PD/mono A/B)
  4. benchmarks/data_train_bench.py  -> DATA_BENCH.json (images/s, wait)

Stops early (still writing whatever was captured) if the first step lands
on the CPU fallback — the pool is wedged and burning the budget on two
more wedged inits helps nobody. Usage: python benchmarks/capture_tpu_all.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(script: str, budget_env: tuple[str, str]) -> dict | None:
    env = dict(os.environ)
    env[budget_env[0]] = budget_env[1]
    r = subprocess.run([sys.executable, os.path.join(_ROOT, script)],
                       capture_output=True, text=True, env=env, cwd=_ROOT)
    line = (r.stdout or "").strip().splitlines()
    for ln in reversed(line):
        try:
            return json.loads(ln)
        except (json.JSONDecodeError, ValueError):
            continue
    print(f"{script}: no JSON output (rc={r.returncode})", file=sys.stderr)
    print((r.stderr or "")[-500:], file=sys.stderr)
    return None


def main() -> int:
    # host-plane collective bench first: it needs no chip (the ring moves
    # host tensors), so it must not be hostage to a wedged pool — and its
    # children are pinned to CPU so a wedged pool can't block jax import
    coll = run("benchmarks/collective_bench.py", ("JAX_PLATFORMS", "cpu"))
    print("collective:", ((coll or {}).get("worlds") or {}))
    out = run("bench.py", ("RAY_TPU_BENCH_BUDGET_S", "540"))
    backend = ((out or {}).get("detail") or {}).get("backend")
    print("bench:", backend, (out or {}).get("value"))
    if backend != "tpu":
        print("pool still wedged; skipping the serving/data captures")
        return 1
    rc = 0
    llm = run("benchmarks/llm_serving_bench.py",
              ("RAY_TPU_LLM_BENCH_BUDGET_S", "540"))
    print("llm:", (llm or {}).get("backend"),
          (llm or {}).get("aggregate_tokens_per_s"))
    if (llm or {}).get("backend") != "tpu":
        rc = 2  # pool died mid-capture: the artifact is a CPU fallback
    load = run("benchmarks/llm_load_bench.py",
               ("RAY_TPU_LLM_LOAD_BENCH_BUDGET_S", "540"))
    print("pd:", (load or {}).get("backend"),
          ((load or {}).get("ab") or {}).get("tokens_per_s_ratio"),
          "decode_step ragged x",
          ((load or {}).get("decode_step") or {}).get("speedup"))
    if (load or {}).get("backend") != "tpu":
        rc = 2
    data = run("benchmarks/data_train_bench.py",
               ("RAY_TPU_DATA_BENCH_BUDGET_S", "540"))
    print("data:", (data or {}).get("backend"),
          (data or {}).get("images_per_sec"),
          "wait", (data or {}).get("device_wait_frac"))
    if (data or {}).get("backend") != "tpu":
        rc = 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
