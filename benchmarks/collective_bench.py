"""Host-collective bench: ring allreduce time + per-rank bytes vs world
size. The ring moves ~2*(W-1)/W * N bytes per rank regardless of W; the
old rendezvous-star moved W*N through one actor.

Usage: python benchmarks/collective_bench.py [mb] [worlds...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ray_tpu
from ray_tpu.util import collective as col_mod


@ray_tpu.remote
class Bench:
    def init_collective_group(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        self.col = col
        col.init_collective_group(world_size, rank, backend=backend,
                                  group_name=group_name)
        self.rank = rank
        self.g = group_name

    def run(self, n_float32, iters=3):
        x = np.ones((n_float32,), np.float32) * (self.rank + 1)
        self.col.allreduce(x, group_name=self.g, timeout=300.0)  # warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = self.col.allreduce(x, group_name=self.g, timeout=300.0)
        dt = (time.perf_counter() - t0) / iters
        return dt, float(out[0])


def main():
    mb = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    worlds = [int(w) for w in sys.argv[2:]] or [2, 4]
    n = int(mb * (1 << 20) / 4)
    ray_tpu.init(num_cpus=32, num_workers=2, max_workers=12)
    for w in worlds:
        actors = [Bench.remote() for _ in range(w)]
        col_mod.create_collective_group(actors, w, list(range(w)),
                                        group_name=f"bench{w}")
        outs = ray_tpu.get([a.run.remote(n) for a in actors], timeout=600)
        dt = max(o[0] for o in outs)
        expect = w * (w + 1) / 2
        assert all(o[1] == expect for o in outs), outs
        per_rank_mb = 2 * (w - 1) / w * mb
        print(json.dumps({
            "world": w, "tensor_mb": mb, "sec_per_allreduce": round(dt, 3),
            "per_rank_transfer_mb": round(per_rank_mb, 2),
            "agg_bandwidth_mb_s": round(w * per_rank_mb / dt, 1)}))
        for a in actors:
            ray_tpu.kill(a)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
