"""Host-collective bench: ring allreduce time + bytes-on-wire vs world
size and wire compression. The ring moves ~2*(W-1)/W * N bytes per rank
regardless of W (the old rendezvous-star moved W*N through one actor);
compression="int8_block" (EQuARX-style, quantization.py) cuts that ~3.9x
again. Bytes are MEASURED from ray_tpu_collective_bytes_total inside the
worker, not computed from the formula.

Rows land in MICROBENCH.json as `collective_*` (merge-preserving, like
the other benches) and the last stdout line is a one-object summary for
capture_tpu_all.py.

Usage: python benchmarks/collective_bench.py [mb] [worlds...]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ray_tpu
from ray_tpu.util import collective as col_mod


@ray_tpu.remote
class Bench:
    def init_collective_group(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        self.col = col
        col.init_collective_group(world_size, rank, backend=backend,
                                  group_name=group_name)
        self.rank = rank
        self.g = group_name

    def run(self, n_float32, compression, iters=3):
        from ray_tpu.util import metrics as met

        x = np.ones((n_float32,), np.float32) * (self.rank + 1)
        self.col.allreduce(x, group_name=self.g, timeout=300.0,
                           compression=compression)  # warm
        counter = met.get_or_create(met.Counter,
                                    "ray_tpu_collective_bytes_total")
        tag = ("compression", compression or "none")
        before = sum(v for tags, v in counter._snapshot_series()
                     if tag in tags)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = self.col.allreduce(x, group_name=self.g, timeout=300.0,
                                     compression=compression)
        dt = (time.perf_counter() - t0) / iters
        after = sum(v for tags, v in counter._snapshot_series()
                    if tag in tags)
        return dt, float(out[0]), (after - before) / iters


def bench_world(w: int, n: int, mb: float) -> dict:
    actors = [Bench.remote() for _ in range(w)]
    col_mod.create_collective_group(actors, w, list(range(w)),
                                    group_name=f"bench{w}")
    out = {"world": w, "tensor_mb": mb}
    for compression in (None, "int8_block"):
        outs = ray_tpu.get([a.run.remote(n, compression) for a in actors],
                           timeout=600)
        dt = max(o[0] for o in outs)
        expect = w * (w + 1) / 2
        if compression is None:
            assert all(o[1] == expect for o in outs), outs
        else:
            assert all(abs(o[1] - expect) < 0.05 * expect for o in outs), outs
        wire_mb = max(o[2] for o in outs) / (1 << 20)  # per-rank, measured
        mode = compression or "fp32"
        out[mode] = {
            "sec_per_allreduce": round(dt, 4),
            "per_rank_wire_mb": round(wire_mb, 3),
            "agg_bandwidth_mb_s": round(w * wire_mb / dt, 1),
        }
    out["wire_ratio"] = round(out["fp32"]["per_rank_wire_mb"]
                              / out["int8_block"]["per_rank_wire_mb"], 2)
    for a in actors:
        ray_tpu.kill(a)
    return out


def main():
    mb = float(sys.argv[1]) if len(sys.argv) > 1 else 8.0
    worlds = [int(w) for w in sys.argv[2:]] or [2, 4]
    n = int(mb * (1 << 20) / 4)
    ray_tpu.init(num_cpus=32, num_workers=2, max_workers=12)
    rows, results = [], []
    for w in worlds:
        r = bench_world(w, n, mb)
        results.append(r)
        print(json.dumps(r))
        for mode in ("fp32", "int8_block"):
            m = r[mode]
            prefix = f"collective_allreduce_w{w}_{int(mb)}mb_{mode}"
            rows += [
                {"name": prefix, "ops_per_s": None, "value": None,
                 "us_per_op": round(m["sec_per_allreduce"] * 1e6, 1)},
                {"name": prefix + "_wire_mb", "ops_per_s": None,
                 "value": m["per_rank_wire_mb"], "us_per_op": None},
                {"name": prefix + "_agg_mb_s",
                 "ops_per_s": m["agg_bandwidth_mb_s"], "value": None,
                 "us_per_op": None},
            ]
        rows.append({"name": f"collective_allreduce_w{w}_{int(mb)}mb"
                             "_int8_wire_ratio",
                     "ops_per_s": None, "value": r["wire_ratio"],
                     "us_per_op": None})
    ray_tpu.shutdown()

    from ray_tpu._private.ray_perf import merge_microbench

    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)
    # one-line summary for capture_tpu_all.py (last stdout JSON line)
    print(json.dumps({
        "bench": "collective", "tensor_mb": mb,
        "worlds": {str(r["world"]): {
            "fp32_sec": r["fp32"]["sec_per_allreduce"],
            "int8_sec": r["int8_block"]["sec_per_allreduce"],
            "wire_ratio": r["wire_ratio"]} for r in results},
    }))


if __name__ == "__main__":
    main()
