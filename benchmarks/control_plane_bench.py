"""Control-plane ceilings: what the single GCS process sustains.

VERDICT round-3 item 9: publish measured ceilings (actors, concurrent
placement groups, virtual nodes) so the next scaling fix is data-driven.
Reference envelope (release/benchmarks/README.md): many_actors 10k,
many_pgs 1k, many_nodes 250 (multi-node); single_node 10k queued tasks.

Method on the 1-core box: batched creation, recording the per-step rate
SERIES (first/min/last) so a mid-run knee is visible in the artifact, plus
an end-to-end liveness probe at peak scale. Results land in
MICROBENCH.json.
"""

from __future__ import annotations

import json
import os
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def bench_actors(max_actors: int = 2000, step: int = 250) -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_workers=2, max_workers=4)

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    # actors beyond worker capacity queue as pending — the ceiling here is
    # GCS bookkeeping (registration + state machine), matching the
    # reference's many_actors envelope semantics
    handles = []
    rates = []
    out: dict = {}
    try:
        while len(handles) < max_actors:
            t0 = time.perf_counter()
            handles.extend(A.remote() for _ in range(step))
            dt = time.perf_counter() - t0
            rates.append(step / dt)
        # liveness under load: one round trip through the first actors
        t0 = time.perf_counter()
        assert ray_tpu.get(handles[0].ping.remote(), timeout=120) == 1
        ping_ms = (time.perf_counter() - t0) * 1e3
        out = {
            "actors_registered": len(handles),
            "actor_submit_per_s_first": round(rates[0], 1),
            "actor_submit_per_s_min": round(min(rates), 1),
            "actor_submit_per_s_last": round(rates[-1], 1),
            "actor_ping_ms_at_peak": round(ping_ms, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def bench_pgs(max_pgs: int = 600, step: int = 100) -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=10_000, num_workers=0, max_workers=1)
    pgs = []
    rates = []
    out: dict = {}
    try:
        while len(pgs) < max_pgs:
            t0 = time.perf_counter()
            for _ in range(step):
                pgs.append(ray_tpu.util.placement_group(
                    [{"CPU": 1.0}], strategy="PACK"))
            dt = time.perf_counter() - t0
            rates.append(step / dt)
        ray_tpu.get(pgs[-1].ready(), timeout=120)
        t0 = time.perf_counter()
        for pg in pgs[: step]:
            ray_tpu.util.remove_placement_group(pg)
        removal_rate = step / (time.perf_counter() - t0)
        out = {
            "pgs_created": len(pgs),
            "pg_create_per_s_first": round(rates[0], 1),
            "pg_create_per_s_min": round(min(rates), 1),
            "pg_create_per_s_last": round(rates[-1], 1),
            "pg_remove_per_s": round(removal_rate, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def bench_nodes(max_nodes: int = 500, step: int = 100) -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.init(num_cpus=2, num_workers=0, max_workers=1)
    cluster = Cluster(initialize_head=False)
    rates = []
    out: dict = {}
    n = 0
    try:
        while n < max_nodes:
            t0 = time.perf_counter()
            for _ in range(step):
                cluster.add_node(num_cpus=4.0)
                n += 1
            rates.append(step / (time.perf_counter() - t0))
        from ray_tpu._private.api import _get_worker

        t0 = time.perf_counter()
        nodes = _get_worker().list_nodes()
        list_ms = (time.perf_counter() - t0) * 1e3
        out = {
            # excludes the head node: virtual nodes this bench added
            "nodes_added": len(nodes) - 1,
            "node_add_per_s_first": round(rates[0], 1),
            "node_add_per_s_min": round(min(rates), 1),
            "node_add_per_s_last": round(rates[-1], 1),
            "list_nodes_ms_at_peak": round(list_ms, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def main():
    results = {}
    results.update(bench_actors())
    results.update(bench_pgs())
    results.update(bench_nodes())
    print(json.dumps(results))
    from ray_tpu._private.ray_perf import merge_microbench

    rows = [{"name": f"ceiling_{k}", "ops_per_s": None, "value": v,
             "us_per_op": None} for k, v in results.items()]
    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)


if __name__ == "__main__":
    main()
