"""Control-plane ceilings: what the single GCS process sustains.

VERDICT round-3 item 9 / round-4 item 4: publish measured ceilings
(actors, concurrent placement groups, virtual nodes, deep task queue) at
the reference envelope so the next scaling fix is data-driven.
Reference envelope (release/benchmarks/README.md): many_actors 10k+,
many_pgs 1k, many_nodes 250 (multi-node, 2k virtual here); deep queue 1M
queued tasks drained.

Method on the 1-core box: batched creation, recording the per-step rate
SERIES (first/min/last) so a mid-run knee is visible in the artifact, plus
an end-to-end liveness probe at peak scale. Results land in
MICROBENCH.json.
"""

from __future__ import annotations

import json
import os
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def bench_actors(max_actors: int = 10_000, step: int = 500) -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_workers=2, max_workers=4)

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    # actors beyond worker capacity queue as pending — the ceiling here is
    # GCS bookkeeping (registration + state machine), matching the
    # reference's many_actors envelope semantics
    handles = []
    rates = []
    out: dict = {}
    try:
        while len(handles) < max_actors:
            t0 = time.perf_counter()
            handles.extend(A.remote() for _ in range(step))
            dt = time.perf_counter() - t0
            rates.append(step / dt)
        # liveness under load: one round trip through the first actors
        t0 = time.perf_counter()
        assert ray_tpu.get(handles[0].ping.remote(), timeout=120) == 1
        ping_ms = (time.perf_counter() - t0) * 1e3
        out = {
            "actors_registered": len(handles),
            "actor_submit_per_s_first": round(rates[0], 1),
            "actor_submit_per_s_min": round(min(rates), 1),
            "actor_submit_per_s_last": round(rates[-1], 1),
            "actor_ping_ms_at_peak": round(ping_ms, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def bench_pgs(max_pgs: int = 1200, step: int = 100) -> dict:
    import ray_tpu

    ray_tpu.init(num_cpus=10_000, num_workers=0, max_workers=1)
    pgs = []
    rates = []
    out: dict = {}
    try:
        while len(pgs) < max_pgs:
            t0 = time.perf_counter()
            for _ in range(step):
                pgs.append(ray_tpu.util.placement_group(
                    [{"CPU": 1.0}], strategy="PACK"))
            dt = time.perf_counter() - t0
            rates.append(step / dt)
        ray_tpu.get(pgs[-1].ready(), timeout=120)
        t0 = time.perf_counter()
        for pg in pgs[: step]:
            ray_tpu.util.remove_placement_group(pg)
        removal_rate = step / (time.perf_counter() - t0)
        out = {
            "pgs_created": len(pgs),
            "pg_create_per_s_first": round(rates[0], 1),
            "pg_create_per_s_min": round(min(rates), 1),
            "pg_create_per_s_last": round(rates[-1], 1),
            "pg_remove_per_s": round(removal_rate, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def bench_nodes(max_nodes: int = 2000, step: int = 200) -> dict:
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.init(num_cpus=2, num_workers=0, max_workers=1)
    cluster = Cluster(initialize_head=False)
    rates = []
    out: dict = {}
    n = 0
    try:
        while n < max_nodes:
            t0 = time.perf_counter()
            for _ in range(step):
                cluster.add_node(num_cpus=4.0)
                n += 1
            rates.append(step / (time.perf_counter() - t0))
        from ray_tpu._private.api import _get_worker

        t0 = time.perf_counter()
        nodes = _get_worker().list_nodes()
        list_ms = (time.perf_counter() - t0) * 1e3
        out = {
            # excludes the head node: virtual nodes this bench added
            "nodes_added": len(nodes) - 1,
            "node_add_per_s_first": round(rates[0], 1),
            "node_add_per_s_min": round(min(rates), 1),
            "node_add_per_s_last": round(rates[-1], 1),
            "list_nodes_ms_at_peak": round(list_ms, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def bench_deep_queue(n_deep: int = 1_000_000, chunk: int = 100_000) -> dict:
    """Submit n_deep tasks behind blocked workers, then drain them all.

    Reference envelope: 1M queued tasks (release/benchmarks/README.md:29).
    Records the submit-rate SERIES per chunk (a knee from per-event queue
    scans or memory pressure shows up as first>>last) plus the drain rate
    and peak RSS.
    """
    os.environ.setdefault("RAY_TPU_DIRECT_DISPATCH", "0")
    import resource
    import tempfile
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_workers=2, max_workers=2)

    @ray_tpu.remote
    def blocker(path):
        import time as _t
        open(path, "w").close()
        while not os.path.exists(path + ".go"):
            _t.sleep(0.05)
        return "unblocked"

    @ray_tpu.remote
    def noop():
        return 0

    d = tempfile.mkdtemp(prefix="cpbench")
    marks = [os.path.join(d, f"b{i}") for i in range(2)]
    blockers = [blocker.remote(m) for m in marks]
    deadline = time.time() + 30
    while not all(os.path.exists(m) for m in marks):
        if time.time() > deadline:
            raise RuntimeError("blockers never started")
        time.sleep(0.05)

    refs = []
    rates = []
    out: dict = {}
    try:
        while len(refs) < n_deep:
            t0 = time.perf_counter()
            refs.extend(noop.remote() for _ in range(chunk))
            rates.append(chunk / (time.perf_counter() - t0))
        rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
        t1 = time.perf_counter()
        for m in marks:
            open(m + ".go", "w").close()
        ray_tpu.get(blockers, timeout=1200)
        ray_tpu.get(refs, timeout=1200)
        drain_rate = n_deep / (time.perf_counter() - t1)
        out = {
            "deep_queue_tasks": len(refs),
            "deep_submit_per_s_first": round(rates[0], 1),
            "deep_submit_per_s_min": round(min(rates), 1),
            "deep_submit_per_s_last": round(rates[-1], 1),
            "deep_drain_per_s": round(drain_rate, 1),
            "deep_queue_driver_rss_mb": round(rss_mb, 1),
        }
    finally:
        ray_tpu.shutdown()
    return out


def main():
    results = {}
    results.update(bench_actors())
    results.update(bench_pgs())
    results.update(bench_nodes())
    results.update(bench_deep_queue())
    print(json.dumps(results))
    from ray_tpu._private.ray_perf import merge_microbench

    rows = [{"name": f"ceiling_{k}", "ops_per_s": None, "value": v,
             "us_per_op": None} for k, v in results.items()]
    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)


if __name__ == "__main__":
    main()
