"""Compiled-DAG step latency: channel plane vs the `.remote()` chain.

The channel execution plane provisions per-actor exec loops over
mutable-shm channels at compile time, so a steady-state step is one
channel write + one channel read with intermediates flowing actor→actor —
no task submission, no GCS, no object store (ROADMAP: ≥5× over the
equivalent `.remote()` chain on a 4-actor pipeline; the tier-1 test
asserts a loose ≥2× to absorb CI noise, this bench tracks the real
number).

Measures, on the same 4 actors:
- `.remote()` chain: one submit per stage per step, get() at the end;
- compiled sync: execute().result() per step (step LATENCY);
- compiled pipelined: max_inflight overlapped executions (step THROUGHPUT).

Instrumentation overhead (ISSUE 4): the channel hot path now carries
always-on per-phase histograms plus every-Nth-step span sampling
(`RayConfig.dag_metrics` / `dag_span_sample_every`). The knobs are stamped
into the exec-loop plans at COMPILE time, so the bench A/B-tests them in
ONE session by recompiling per round, alternating instrumented (default
settings) and uninstrumented rounds — interleaving cancels the scheduling
drift of a small shared box, which otherwise swamps a ≤5% effect. The
pooled median-step delta is reported as
`dag_instrumentation_overhead_pct` (budget ≤5%).

JSON on stdout + rows merged into MICROBENCH.json like the other benches.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_STAGES = 4
WARMUP = 25
STEPS = 400


def _measure_channel(actors, n_steps, warmup, with_pipelined=True):
    """(step seconds list, pipelined_us) for the channel plane on live
    actors. The overhead-baseline session skips the pipelined sweep — only
    the median sync step feeds the comparison."""
    import ray_tpu  # noqa: F401 — session already up
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        node = inp
        for a in actors:
            node = a.work.bind(node)
    compiled = node.experimental_compile(max_inflight_executions=8)
    assert compiled.uses_channels, compiled.fallback_reason
    for i in range(warmup):
        compiled.execute(i).result(timeout=120)
    chan_steps = []
    for i in range(n_steps):
        t0 = time.perf_counter()
        compiled.execute(i).result(timeout=120)
        chan_steps.append(time.perf_counter() - t0)
    piped_us = None
    if with_pipelined:
        # pipelined throughput: overlapped in-flight executions
        t0 = time.perf_counter()
        futs = [compiled.execute_async(i) for i in range(n_steps)]
        for f in futs:
            f.result(timeout=120)
        piped_us = (time.perf_counter() - t0) / n_steps * 1e6
    compiled.teardown()
    return chan_steps, piped_us


def _alternating_overhead(actors, steps_per_round=100, warmup=10,
                          rounds=4):
    """Pooled step samples for instrumented-vs-uninstrumented, interleaved
    round-robin in one session (compile → measure → teardown per round)."""
    from ray_tpu._private.ray_config import RayConfig

    knobs = ("RAY_TPU_DAG_METRICS", "RAY_TPU_DAG_SPAN_SAMPLE_EVERY")
    saved = {k: os.environ.get(k) for k in knobs}
    samples = {"on": [], "off": []}
    try:
        for _ in range(rounds):
            for mode in ("on", "off"):
                if mode == "off":
                    os.environ["RAY_TPU_DAG_METRICS"] = "0"
                    os.environ["RAY_TPU_DAG_SPAN_SAMPLE_EVERY"] = "0"
                else:
                    # FORCE default instrumentation settings (pop any
                    # ambient override): a shell that exports
                    # RAY_TPU_DAG_METRICS=0 must not turn the A/B
                    # comparison into off-vs-off
                    for k in knobs:
                        os.environ.pop(k, None)
                RayConfig.reset()
                steps, _ = _measure_channel(actors, steps_per_round, warmup,
                                            with_pipelined=False)
                samples[mode].extend(steps)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        RayConfig.reset()
    return samples


def bench_dag(n_steps: int = STEPS, warmup: int = WARMUP) -> dict:
    # step latency is reported as the per-step MEDIAN (scheduling tails
    # on small hosts make means noisy); means ride along for reference
    import ray_tpu

    ray_tpu.init(num_cpus=16, num_workers=N_STAGES, max_workers=8)

    @ray_tpu.remote
    class Stage:
        def __init__(self, bias):
            self.bias = bias

        def work(self, x):
            return x + self.bias

    try:
        actors = [Stage.remote(1) for _ in range(N_STAGES)]
        for a in actors:
            a.__ray_ready__()

        # ---- baseline: the equivalent .remote() chain, one step at a time
        def chain_step(x):
            ref = x
            for a in actors:
                ref = a.work.remote(ref)
            return ray_tpu.get(ref, timeout=120)

        for i in range(warmup):
            chain_step(i)
        remote_steps = []
        for i in range(n_steps):
            t0 = time.perf_counter()
            chain_step(i)
            remote_steps.append(time.perf_counter() - t0)

        # ---- channel plane at default instrumentation (headline numbers)
        chan_steps, piped_us = _measure_channel(actors, n_steps, warmup)

        # ---- instrumentation overhead: interleaved A/B rounds
        ab = _alternating_overhead(actors)
    finally:
        ray_tpu.shutdown()

    remote_us = statistics.median(remote_steps) * 1e6
    chan_us = statistics.median(chan_steps) * 1e6
    instr_us = statistics.median(ab["on"]) * 1e6
    bare_us = statistics.median(ab["off"]) * 1e6
    return {
        "dag_stages": N_STAGES,
        "dag_steps": n_steps,
        "dag_remote_chain_step_us": round(remote_us, 1),
        "dag_channel_step_us": round(chan_us, 1),
        "dag_remote_chain_step_mean_us": round(
            sum(remote_steps) / n_steps * 1e6, 1),
        "dag_channel_step_mean_us": round(
            sum(chan_steps) / n_steps * 1e6, 1),
        "dag_channel_pipelined_step_us": round(piped_us, 1),
        "dag_channel_speedup": round(remote_us / chan_us, 2),
        "dag_channel_pipelined_speedup": round(remote_us / piped_us, 2),
        # instrumented (default sampling) vs uninstrumented channel step,
        # pooled over interleaved rounds: the ≤5% budget from ISSUE 4
        "dag_channel_step_instrumented_us": round(instr_us, 1),
        "dag_channel_step_uninstrumented_us": round(bare_us, 1),
        "dag_instrumentation_overhead_pct": round(
            (instr_us - bare_us) / bare_us * 100.0, 2),
    }


def main():
    results = bench_dag()
    print(json.dumps(results))
    from ray_tpu._private.ray_perf import merge_microbench

    rows = [
        {"name": "dag_remote_chain_step", "ops_per_s": None, "value": None,
         "us_per_op": results["dag_remote_chain_step_us"]},
        {"name": "dag_channel_step", "ops_per_s": None, "value": None,
         "us_per_op": results["dag_channel_step_us"]},
        {"name": "dag_channel_pipelined_step", "ops_per_s": None,
         "value": None,
         "us_per_op": results["dag_channel_pipelined_step_us"]},
        {"name": "dag_channel_speedup", "ops_per_s": None,
         "value": results["dag_channel_speedup"], "us_per_op": None},
        {"name": "dag_channel_step_uninstrumented", "ops_per_s": None,
         "value": None,
         "us_per_op": results["dag_channel_step_uninstrumented_us"]},
        {"name": "dag_instrumentation_overhead_pct", "ops_per_s": None,
         "value": results["dag_instrumentation_overhead_pct"],
         "us_per_op": None},
    ]
    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)


if __name__ == "__main__":
    main()
