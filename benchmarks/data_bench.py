"""Data-plane fault-tolerance A/B: supervision overhead on a HEALTHY
pipeline (ISSUE 20 satellite).

(reference gate: Ray Data enables per-block retry + actor-pool
supervision unconditionally because its bookkeeping is noise next to
the work it protects — python/ray/data/_internal/execution/. Here: the
same streaming pipeline runs with ``data_fault_tolerance`` on and off,
INTERLEAVED on/off/on/off so drift hits both arms equally, and the
median overhead of the FT arm must stay ≤5%. The pipeline uses MANY
small blocks: FT bookkeeping is per-dispatch (probe ready refs, retain
inputs, attempt accounting), so block count is the axis it scales
with — and the drain must dwarf the one-off actor-pool spin-up whose
0.1-0.6s jitter would otherwise drown the signal.)

The FT arm pays for: per-ready-ref error probes (`_probe_ready`), the
retained-input ledger for in-flight re-dispatch, attempt/backoff
bookkeeping, and the pool liveness sweep. None of that should be
visible on a pipeline where nothing fails.

Merges the ``fault_tolerance`` section into DATA_BENCH.json via
``merge_artifact`` (the llm_load_bench discipline) — data_train_bench's
``results`` section survives a rerun of this script and vice versa.

Exit status is the assertion: nonzero when overhead exceeds the bar
(override the bar with RAY_TPU_DATA_AB_MAX_OVERHEAD_PCT).
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRIALS = int(os.environ.get("RAY_TPU_DATA_AB_TRIALS", "5"))
_ROWS = int(os.environ.get("RAY_TPU_DATA_AB_ROWS", "64000"))
_BLOCKS = int(os.environ.get("RAY_TPU_DATA_AB_BLOCKS", "64"))
_MAX_OVERHEAD_PCT = float(
    os.environ.get("RAY_TPU_DATA_AB_MAX_OVERHEAD_PCT", "5.0"))


def _udf():
    # closure so it pickles by value (workers cannot import __main__
    # reliably across spawn configs); batch-sized arithmetic keeps the
    # work real but the runtime dominated by dispatch + transfer — the
    # regime where FT bookkeeping overhead would actually show up
    def fn(batch):
        import numpy as _np

        v = _np.asarray(batch["id"], dtype=_np.float64)
        for _ in range(8):
            v = _np.sqrt(v * v + 1.0)
        return {"id": batch["id"], "v": v}

    return fn


def _run_once(ft_on: bool) -> float:
    """One full pipeline drain under the given FT setting; returns
    wall seconds. The executor reads RayConfig at execute() time, so an
    env flip + reset() retoggles without a cluster restart."""
    from ray_tpu import data as rd
    from ray_tpu._private.ray_config import RayConfig

    os.environ["RAY_TPU_DATA_FAULT_TOLERANCE"] = "1" if ft_on else "0"
    RayConfig.reset()
    try:
        ds = rd.range(_ROWS, parallelism=_BLOCKS).map_batches(
            _udf(), compute="actors", concurrency=2)
        t0 = time.perf_counter()
        rows = ds.take_all()
        dt = time.perf_counter() - t0
        assert len(rows) == _ROWS
        return dt
    finally:
        os.environ.pop("RAY_TPU_DATA_FAULT_TOLERANCE", None)
        RayConfig.reset()


def _measure() -> dict:
    import ray_tpu

    # keep worker processes warm across actor-pool generations: each
    # trial builds a fresh 2-actor pool, and cold worker spawns would
    # otherwise dominate the sub-second drains being compared
    os.environ.setdefault("RAY_TPU_WARM_POOL_SIZE", "4")
    ray_tpu.init(num_cpus=8, num_workers=4, max_workers=8)
    try:
        _run_once(True)   # warm both arms: imports, pool, page cache
        _run_once(False)
        on_s: list[float] = []
        off_s: list[float] = []
        for i in range(_TRIALS):
            # alternate which arm goes first so slow-drift (page cache,
            # thermal, background load) cannot favor one side
            order = (True, False) if i % 2 == 0 else (False, True)
            for ft in order:
                (on_s if ft else off_s).append(_run_once(ft))
        med_on = statistics.median(on_s)
        med_off = statistics.median(off_s)
        overhead_pct = (med_on - med_off) / med_off * 100.0
        return {
            "rows": _ROWS,
            "blocks": _BLOCKS,
            "trials": _TRIALS,
            "ft_on_median_s": round(med_on, 4),
            "ft_off_median_s": round(med_off, 4),
            "ft_on_s": [round(s, 4) for s in on_s],
            "ft_off_s": [round(s, 4) for s in off_s],
            "overhead_pct": round(overhead_pct, 2),
            "max_overhead_pct": _MAX_OVERHEAD_PCT,
            "overhead_ok": bool(overhead_pct <= _MAX_OVERHEAD_PCT),
        }
    finally:
        ray_tpu.shutdown()


def main() -> int:
    sys.path.insert(0, _ROOT)
    from ray_tpu.scripts._artifacts import merge_artifact

    out = _measure()
    path = merge_artifact("DATA_BENCH.json", "fault_tolerance", out)
    print(json.dumps(out))
    if not out["overhead_ok"]:
        print(f"FAIL: FT-on overhead {out['overhead_pct']}% exceeds "
              f"{_MAX_OVERHEAD_PCT}% bar ({path})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
