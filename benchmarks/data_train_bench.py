"""Data→train pipeline benchmark: BASELINE config 3 (image pipeline
feeding HBM prefetch).

(reference gate: release/release_tests.yaml:1670-1721 — the
multimodal/image-pipeline release tests assert the data plane keeps the
accelerator fed; their acceptance metric is throughput with the GPU not
starving. Here: image files → decode → augment (remote workers, CPU) →
streaming_split → driver-side train step on the chip with a device-put
prefetch window; we record images/s end-to-end and the DEVICE-WAIT
FRACTION — the share of wall time the train loop blocks on the data plane
instead of stepping. Bar: device_wait_frac < 0.10.)

Same capture hardening as bench.py: the TPU measurement runs in a child
with a hard deadline, a CPU child still records the pipeline shape when
the pool is wedged, and the last-known-good TPU result is cached. Writes
DATA_BENCH.json at the repo root.
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

_LKG_PATH = "/tmp/ray_tpu_data_bench_last_good.json"
_BUDGET_S = float(os.environ.get("RAY_TPU_DATA_BENCH_BUDGET_S", "540"))
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_corpus(d: str, n: int, size: int) -> list[str]:
    """Synthesize a JPEG shard corpus (decode cost is the point)."""
    import numpy as np
    from PIL import Image

    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(0)
    paths = []
    for i in range(n):
        p = os.path.join(d, f"img{i:05d}.jpg")
        if not os.path.exists(p):
            arr = rng.integers(0, 256, (size, size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(p, quality=85)
        paths.append(p)
    return paths


def _measure(platform: str) -> dict:
    import numpy as np

    os.environ.setdefault("RAY_TPU_WARM_POOL_SIZE", "2")
    import jax
    import jax.numpy as jnp
    import optax

    import ray_tpu
    import ray_tpu.data as rdata
    from ray_tpu.models import vit

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # ViT-L/16: the step must be heavy enough that ONE host core's
        # JPEG decode (~200 img/s) can keep the chip fed — the release
        # gate's criterion is overlap, and a too-small model on a 1-core
        # host measures the host, not the pipeline
        img, batch, n_imgs, epochs = 224, 32, 512, 3
        cfg = vit.vit_config("l16", image_size=img, num_classes=1000,
                             dtype=jnp.bfloat16)
    else:
        img, batch, n_imgs, epochs = 64, 16, 96, 2
        cfg = vit.vit_config("s16", image_size=img, num_classes=16,
                             d_model=128, n_layers=2, n_heads=4, d_ff=256,
                             dtype=jnp.float32)

    corpus = _make_corpus(f"/tmp/ray_tpu_imgbench_{img}", n_imgs, 256)
    # worker processes must NOT touch the chip: the driver owns it, the
    # decode/augment tasks are host-side (the Node spawner injects
    # JAX_PLATFORMS=cpu into workers — ray_tpu/_private/node.py)
    ray_tpu.init(num_cpus=4, num_workers=3, max_workers=4)

    def augment(b):
        imgs = b["image"].astype(np.float32) / 255.0
        # random crop to the train size + horizontal flip: the classic
        # input-pipeline cost the release gate exercises
        rng = np.random.default_rng(int(b["image"].sum()) & 0xFFFF)
        h = rng.integers(0, imgs.shape[1] - img + 1)
        w = rng.integers(0, imgs.shape[2] - img + 1)
        imgs = imgs[:, h:h + img, w:w + img, :]
        if rng.random() < 0.5:
            imgs = imgs[:, :, ::-1, :]
        labels = rng.integers(0, cfg.num_classes, imgs.shape[0])
        return {"image": np.ascontiguousarray(imgs), "label": labels}

    params = vit.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(math.prod(p.shape) for p in jax.tree.leaves(params))
    opt = optax.adamw(1e-4)
    opt_state = opt.init(params)

    def loss_fn(p, images, labels):
        logits = vit.forward(p, images, cfg)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(lp, labels[:, None], axis=1).mean()

    @jax.jit
    def step(p, s, images, labels):
        loss, grads = jax.value_and_grad(loss_fn)(p, images, labels)
        upd, s = opt.update(grads, s, p)
        return optax.apply_updates(p, upd), s, loss

    def batches():
        """One epoch: read → augment on remote workers → streaming_split →
        device-put prefetch window of 2 (iter_jax_batches semantics,
        driver-side so the split iterator composes)."""
        import collections

        ds = rdata.read_images(corpus).map_batches(augment, batch_size=batch)
        it = ds.streaming_split(1)[0]
        pending: collections.deque = collections.deque()
        for b in it.iter_batches(batch_size=batch):
            if len(b["label"]) < batch:
                continue  # drop ragged tail: jit shapes stay static
            fut = jax.device_put({"image": b["image"],
                                  "label": b["label"].astype(np.int32)})
            pending.append(fut)
            while len(pending) >= 2:
                yield pending.popleft()
        while pending:
            yield pending.popleft()

    # warmup epoch fragment: compile + warm the worker pool
    warm = next(iter(batches()))
    params, opt_state, l0 = step(params, opt_state, warm["image"], warm["label"])
    jax.block_until_ready(l0)

    images_seen = 0
    wait_s = 0.0
    step_s = 0.0
    t_run0 = time.perf_counter()
    loss = None
    for _ in range(epochs):
        gen = batches()
        while True:
            t0 = time.perf_counter()
            try:
                b = next(gen)
            except StopIteration:
                break
            t1 = time.perf_counter()
            params, opt_state, loss = step(params, opt_state,
                                           b["image"], b["label"])
            jax.block_until_ready(loss)
            t2 = time.perf_counter()
            wait_s += t1 - t0
            step_s += t2 - t1
            images_seen += batch
    total = time.perf_counter() - t_run0
    ray_tpu.shutdown()
    return {
        "backend": jax.default_backend(),
        "images_per_sec": round(images_seen / total, 1),
        "device_wait_frac": round(wait_s / total, 4),
        "step_frac": round(step_s / total, 4),
        "images_seen": images_seen,
        "epochs": epochs,
        "batch": batch,
        "image_size": img,
        "model_params": n_params,
        "final_loss": float(loss) if loss is not None else None,
        "device_wait_ok": bool(wait_s / total < 0.10),
    }


def main():
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import _capture

    child = os.environ.get("RAY_TPU_DATA_BENCH_CHILD")
    if child:
        _capture.child_guard("RAY_TPU_DATA_BENCH_CHILD", child)
        _capture.emit(_measure(child))
        return 0

    out = _capture.orchestrate(
        os.path.abspath(__file__), "RAY_TPU_DATA_BENCH_CHILD", _BUDGET_S,
        _LKG_PATH, ["images_per_sec", "device_wait_frac"], _ROOT)
    # merge discipline: DATA_BENCH.json is shared with data_bench.py's
    # `fault_tolerance` A/B section — a rerun here must not clobber it
    sys.path.insert(0, _ROOT)
    from ray_tpu.scripts._artifacts import merge_artifact

    merge_artifact("DATA_BENCH.json", "results", out)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
