"""Cluster event plane overhead: actor churn with events on vs off.

The control-plane event log (ISSUE 19) hangs emission sites off the GCS's
hottest actor paths — _create_actor, dispatch, _on_task_done,
_on_worker_death, _kill_actor — plus DEBUG lease-grant events on every
lease cycle. The budget is ≤5% on control-plane-bound work; this bench
measures it the same way dag_bench measures instrumentation overhead:
alternating on/off rounds (interleaving cancels the scheduling drift of a
small shared box, which otherwise swamps a ≤5% effect), pooling per-cycle
samples, comparing medians.

The enabled flag (`RayConfig.cluster_events`, env
RAY_TPU_CLUSTER_EVENTS) is read once at GCS construction, so unlike the
DAG bench each round is its own session: set the env, reset the config
cache, init, churn, shutdown. A churn cycle = create a batch of actors,
round-trip a ping through each, kill them all — every phase of the actor
lifecycle state machine, which is exactly where the emit sites live.

JSON on stdout + rows merged into MICROBENCH.json like the other benches.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BATCH = 8          # actors per churn cycle (== worker pool capacity)
CYCLES = 12        # churn cycles per round
ROUNDS = 4         # on/off round pairs


def _churn_round(cycles: int = CYCLES, batch: int = BATCH):
    """One session's per-cycle wall times for create→ping→kill churn."""
    import ray_tpu

    ray_tpu.init(num_cpus=2 * batch, num_workers=batch, max_workers=batch)

    @ray_tpu.remote
    class Churn:
        def ping(self):
            return 1

    samples = []
    try:
        # warmup cycle: worker pool spin-up + import costs stay out of the
        # measured samples
        warm = [Churn.remote() for _ in range(batch)]
        ray_tpu.get([a.ping.remote() for a in warm], timeout=120)
        for a in warm:
            ray_tpu.kill(a)
        for _ in range(cycles):
            t0 = time.perf_counter()
            actors = [Churn.remote() for _ in range(batch)]
            ray_tpu.get([a.ping.remote() for a in actors], timeout=120)
            for a in actors:
                ray_tpu.kill(a)
            samples.append(time.perf_counter() - t0)
    finally:
        ray_tpu.shutdown()
    return samples


def bench_events_overhead(rounds: int = ROUNDS) -> dict:
    from ray_tpu._private import events as cluster_events
    from ray_tpu._private.ray_config import RayConfig

    knob = "RAY_TPU_CLUSTER_EVENTS"
    saved = os.environ.get(knob)
    samples = {"on": [], "off": []}
    try:
        for _ in range(rounds):
            for mode in ("on", "off"):
                if mode == "off":
                    os.environ[knob] = "0"
                else:
                    # FORCE the default-on setting (pop any ambient
                    # override): a shell exporting RAY_TPU_CLUSTER_EVENTS=0
                    # must not turn the A/B comparison into off-vs-off
                    os.environ.pop(knob, None)
                RayConfig.reset()
                cluster_events.reset()
                samples[mode].extend(_churn_round())
    finally:
        if saved is None:
            os.environ.pop(knob, None)
        else:
            os.environ[knob] = saved
        RayConfig.reset()
        cluster_events.reset()

    on_ms = statistics.median(samples["on"]) * 1e3
    off_ms = statistics.median(samples["off"]) * 1e3
    return {
        "events_churn_batch": BATCH,
        "events_churn_cycles": len(samples["on"]),
        "events_churn_cycle_on_ms": round(on_ms, 2),
        "events_churn_cycle_off_ms": round(off_ms, 2),
        # the ≤5% acceptance budget from ISSUE 19
        "events_plane_overhead_pct": round(
            (on_ms - off_ms) / off_ms * 100.0, 2),
    }


def main():
    results = bench_events_overhead()
    print(json.dumps(results))
    assert results["events_plane_overhead_pct"] <= 5.0, (
        f"event plane costs {results['events_plane_overhead_pct']}% on "
        f"actor churn (budget 5%)")
    from ray_tpu._private.ray_perf import merge_microbench

    rows = [
        {"name": "events_churn_cycle_on", "ops_per_s": None, "value": None,
         "us_per_op": results["events_churn_cycle_on_ms"] * 1e3},
        {"name": "events_churn_cycle_off", "ops_per_s": None, "value": None,
         "us_per_op": results["events_churn_cycle_off_ms"] * 1e3},
        {"name": "events_plane_overhead_pct", "ops_per_s": None,
         "value": results["events_plane_overhead_pct"], "us_per_op": None},
    ]
    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)


if __name__ == "__main__":
    main()
