"""Closed-loop LLM load harness: arrival-rate sweep + PD-vs-monolithic A/B.

The sustained-load counterpart of llm_serving_bench.py (which measures the
engine's intrinsic TTFT/throughput): this one drives the serving stack the
way traffic does —

- **closed loop**: N client threads, each issuing its next request the
  moment the previous one completes (the A/B mode: PD disaggregation vs
  one monolithic continuous-batching engine at concurrency >= 8);
- **open loop**: Poisson arrivals at a swept rate (req/s), the regime
  where queueing shows up in p99 TTFT long before throughput saturates
  (measurement template: the Gemma-on-TPU serving comparison,
  arXiv 2605.25645 — PAPERS.md).

The PD stack here is the real transfer plane in-process: the prefill
tier (PrefillCoalescer) runs the prompt forward and exports paged KV
through ray_tpu/llm/kv_transfer.py (MutableShmChannel per ticket); the
decode engine admits pages AS THEY ARRIVE through the shared
BatchedKVPuller + streamed submit_prefilled(kv_stream=...). No serve
control plane — the handoff and the slots are what's under test.

Writes the ``pd`` section of LLM_BENCH.json (merging, not clobbering, the
serving bench's fields). Capture hardening identical to
llm_serving_bench.py: self-terminating alarm child, CPU fallback row,
last-known-good TPU cache.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_LKG_PATH = "/tmp/ray_tpu_llm_load_bench_last_good.json"
_BUDGET_S = float(os.environ.get("RAY_TPU_LLM_LOAD_BENCH_BUDGET_S", "540"))
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)  # children run with benchmarks/ as sys.path[0]


# ---------------------------------------------------------------- stacks


class _MonoStack:
    """One continuous-batching paged engine: the baseline."""

    def __init__(self, cfg, params, *, page_size, max_slots, max_len,
                 min_bucket):
        from ray_tpu.llm.engine import TPUEngine

        self.engine = TPUEngine(cfg, params, max_slots=max_slots,
                                max_len=max_len, min_bucket=min_bucket,
                                kv_layout="paged", page_size=page_size)

    def request(self, ids, max_tokens: int):
        from ray_tpu.llm.engine import SamplingParams

        t0 = time.perf_counter()
        req = self.engine.submit(ids, SamplingParams(max_tokens=max_tokens))
        req.out_queue.get()  # first token
        ttft = time.perf_counter() - t0
        n = 1 + sum(1 for _ in req)
        return ttft, n

    def generate(self, ids, max_tokens: int) -> list:
        from ray_tpu.llm.engine import SamplingParams

        return self.engine.generate(ids,
                                    SamplingParams(max_tokens=max_tokens))

    def shutdown(self):
        self.engine.shutdown()


class _PDStack:
    """Disaggregated: the prefill tier coalesces concurrent prompts into
    batched forwards (PrefillCoalescer) and exports paged KV over the shm
    transfer plane; the decode engine admits pages AS THEY ARRIVE through
    the shared batched puller (streamed admission — the production path)."""

    def __init__(self, cfg, params, *, page_size, max_slots, max_len,
                 min_bucket, prefetch_depth: int = 2,
                 prefill_batch_max: int = 4):
        import jax  # noqa: F401 — imported for the device backend

        from ray_tpu.llm.engine import TPUEngine
        from ray_tpu.llm.kv_transfer import BatchedKVPuller, PagedKVExporter
        from ray_tpu.llm.pd import PrefillCoalescer

        self.cfg, self.params = cfg, params
        self.page_size = page_size
        self.min_bucket = max(min_bucket, page_size)
        self.max_len = max_len
        self.exporter = PagedKVExporter(send_timeout_s=120.0,
                                        prefetch_pages=prefetch_depth)
        self.puller = BatchedKVPuller()
        self.coalescer = PrefillCoalescer(
            params, cfg, min_bucket=self.min_bucket, max_len=max_len,
            max_batch=prefill_batch_max)
        self.decode = TPUEngine(cfg, params, max_slots=max_slots,
                                max_len=max_len, min_bucket=self.min_bucket,
                                kv_layout="paged", page_size=page_size)

    def _prefill(self, ids) -> dict:
        import jax.numpy as jnp
        import numpy as np

        logits, k, v, _bucket = self.coalescer.prefill(list(ids))
        first = int(jnp.argmax(logits))  # greedy (temperature 0 workload)
        return self.exporter.export(np.asarray(k), np.asarray(v),
                                    len(ids), first, self.page_size)

    def _submit(self, ticket, max_tokens: int):
        from ray_tpu.llm.engine import SamplingParams
        from ray_tpu.llm.kv_transfer import KVPageStream

        stream = KVPageStream(ticket["n_pages"], ticket["page_size"])
        self.puller.pull(ticket, stream, timeout_s=120.0)
        return self.decode.submit_prefilled(
            length=ticket["length"], first_token=ticket["first_token"],
            params=SamplingParams(max_tokens=max_tokens), kv_stream=stream)

    def request(self, ids, max_tokens: int):
        t0 = time.perf_counter()
        ticket = self._prefill(ids)  # calling thread joins the coalescer
        ttft = time.perf_counter() - t0  # first token rides the ticket
        req = self._submit(ticket, max_tokens)
        n = 1 + sum(1 for _ in req)
        return ttft, n

    def generate(self, ids, max_tokens: int) -> list:
        ticket = self._prefill(ids)
        req = self._submit(ticket, max_tokens)
        return [ticket["first_token"]] + list(req)

    def shutdown(self):
        self.coalescer.teardown()
        self.puller.teardown()
        self.decode.shutdown()
        self.exporter.teardown()


# ---------------------------------------------------------------- drivers


def _phase_totals() -> dict:
    """{phase: (sum_s, count)} for the PD-relevant phase histograms in
    THIS process's metrics registry (the whole harness is in-process).
    Deltas around a round attribute its time: transfer wait, admission
    wait, decode inter-token — the breakdown the next PD-optimization PR
    starts from."""
    from ray_tpu.util import metrics as met

    out: dict = {}
    for m in met.snapshot():
        if m["name"] not in ("ray_tpu_llm_pd_phase_seconds",
                             "ray_tpu_llm_engine_phase_seconds"):
            continue
        for tags, st in m["series"]:
            phase = dict(tuple(t) for t in tags).get("phase")
            s, c = out.get(phase, (0.0, 0))
            out[phase] = (s + st.get("sum", 0.0), c + st.get("count", 0))
    return out


def _phase_breakdown(pre: dict, post: dict, n_requests: int) -> dict:
    """Per-phase mean/total deltas between two _phase_totals snapshots."""
    out: dict = {}
    for phase in ("transfer_wait", "transfer_send_wait", "admission_wait",
                  "inter_token"):
        s0, c0 = pre.get(phase, (0.0, 0))
        s1, c1 = post.get(phase, (0.0, 0))
        if c1 > c0:
            out[phase] = {
                "mean_ms": round((s1 - s0) / (c1 - c0) * 1e3, 4),
                "total_s": round(s1 - s0, 4),
                "count": c1 - c0,
            }
    # derived: where one request's time went on average, the attribution
    # view the PD-vs-monolithic gap analysis needs
    if n_requests:
        for phase, rec in out.items():
            rec["per_request_ms"] = round(
                rec["total_s"] / n_requests * 1e3, 3)
    return out


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _stats(results: list, wall: float) -> dict:
    ttfts = sorted(r[0] for r in results)
    return {
        "requests": len(results),
        "p50_ttft_ms": round(_pct(ttfts, 0.50) * 1e3, 2),
        "p99_ttft_ms": round(_pct(ttfts, 0.99) * 1e3, 2),
        "tokens_per_s": round(sum(r[1] for r in results) / max(wall, 1e-9), 1),
        "wall_s": round(wall, 2),
    }


def _closed_loop(stack, prompts, *, concurrency: int, n_requests: int,
                 max_tokens: int) -> dict:
    """N clients, each firing its next request on completion."""
    results: list = []
    lock = threading.Lock()
    counter = iter(range(n_requests))

    def client():
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            r = stack.request(prompts[i % len(prompts)], max_tokens)
            with lock:
                results.append(r)

    threads = [threading.Thread(target=client) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = _stats(results, time.perf_counter() - t0)
    out["concurrency"] = concurrency
    return out


def _open_loop(stack, prompts, *, rate_rps: float, duration_s: float,
               max_tokens: int, rng) -> dict:
    """Poisson arrivals at rate_rps for duration_s; every arrival gets its
    own client thread (queueing shows up as TTFT, not as lost arrivals)."""
    results: list = []
    lock = threading.Lock()
    threads: list = []
    t0 = time.perf_counter()
    i = 0
    next_at = t0
    while True:
        next_at += rng.exponential(1.0 / rate_rps)
        now = time.perf_counter()
        if next_at - t0 > duration_s:
            break
        if next_at > now:
            time.sleep(next_at - now)

        def client(idx=i):
            r = stack.request(prompts[idx % len(prompts)], max_tokens)
            with lock:
                results.append(r)

        th = threading.Thread(target=client)
        th.start()
        threads.append(th)
        i += 1
    for th in threads:
        th.join()
    out = _stats(results, time.perf_counter() - t0)
    out["rate_rps"] = rate_rps
    out["offered"] = i
    return out


class _AdmissionGate:
    """Replica-admission semantics (serve/replica.py) for an in-process
    stack: at most `max_ongoing` requests executing, at most `max_queued`
    waiting for a slot — anything beyond is SHED with RequestShedError
    instead of queued, exactly what a bounded replica does at 3x load."""

    def __init__(self, max_ongoing: int, max_queued: int):
        self._sem = threading.BoundedSemaphore(max_ongoing)
        self._max_queued = max_queued
        self._pending = 0
        self._lock = threading.Lock()

    def enter(self) -> None:
        from ray_tpu.exceptions import RequestShedError

        if self._sem.acquire(blocking=False):
            return
        with self._lock:
            if self._pending >= self._max_queued:
                raise RequestShedError(
                    f"admission queue full ({self._max_queued} waiting)")
            self._pending += 1
        self._sem.acquire()
        with self._lock:
            self._pending -= 1

    def leave(self) -> None:
        self._sem.release()


def _overload_round(stack, prompts, *, capacity_rps: float, factor: float,
                    duration_s: float, max_tokens: int, max_ongoing: int,
                    max_queued: int, rng) -> dict:
    """Open loop at `factor` x the measured closed-loop capacity against a
    bounded admission gate: the overload row. Records the shed rate and
    the ACCEPTED requests' p99 TTFT — the property under test is that
    bounded admission keeps latency for admitted work flat while excess
    arrivals get a fast refusal, instead of every request drowning in an
    unbounded queue."""
    from ray_tpu.exceptions import RequestShedError

    gate = _AdmissionGate(max_ongoing, max_queued)
    rate = max(capacity_rps * factor, 0.5)
    accepted: list = []
    shed = [0]
    lock = threading.Lock()
    threads: list = []
    t0 = time.perf_counter()
    i = 0
    next_at = t0
    while True:
        next_at += rng.exponential(1.0 / rate)
        now = time.perf_counter()
        if next_at - t0 > duration_s:
            break
        if next_at > now:
            time.sleep(next_at - now)

        def client(idx=i):
            try:
                gate.enter()
            except RequestShedError:
                with lock:
                    shed[0] += 1
                return
            try:
                r = stack.request(prompts[idx % len(prompts)], max_tokens)
            finally:
                gate.leave()
            with lock:
                accepted.append(r)

        th = threading.Thread(target=client)
        th.start()
        threads.append(th)
        i += 1
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    out = _stats(accepted, wall)
    out.update({
        "offered": i,
        "offered_rps": round(rate, 2),
        "capacity_rps": round(capacity_rps, 2),
        "overload_factor": factor,
        "shed": shed[0],
        "shed_rate": round(shed[0] / max(i, 1), 3),
        "max_ongoing": max_ongoing,
        "max_queued": max_queued,
    })
    return out


# ----------------------------------------------------- decode-step microbench


def _decode_step_bench(cfg, params, *, page_size, max_len, batch,
                       lengths, iters=30) -> dict:
    """Ragged vs gather-per-slot decode step on ONE paged state with mixed
    sequence lengths — the kernel-level half of the PD win. The gather
    step's attention walks every row's full [max_pages*page] span; the
    ragged step walks only the batch's live page bound (Pallas kernel on
    TPU, the bit-consistent reference elsewhere)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import decoding, decoding_paged as dp

    P = page_size
    MP = max_len // P
    num_pages = batch * MP + 1
    state = dp.init_paged_state(cfg, batch, max_len, num_pages, P)
    free = list(range(1, num_pages))
    min_bucket = P
    for slot, n in enumerate(lengths):
        bucket = min_bucket
        while bucket < n:
            bucket *= 2
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = 1 + np.arange(n) % (cfg.vocab_size - 2)
        logits, kv = decoding.prefill(params, jnp.asarray(padded),
                                      jnp.int32(n), cfg)
        need = MP  # full reservation: the gather step's worst (usual) case
        pages = [free.pop() for _ in range(need)]
        row = np.zeros((MP,), np.int32)
        row[:need] = pages
        state = dp.insert_sequence_paged(
            state, slot, kv, jnp.int32(n),
            jnp.asarray(int(jnp.argmax(logits)), jnp.int32),
            jnp.asarray(row), cfg)
    on_tpu = jax.default_backend() == "tpu"
    bound = 1
    while bound * P < max(lengths) + iters + 1:
        bound *= 2
    bound = min(bound, MP)

    def run(step):
        st = {k: jnp.array(v) for k, v in state.items()}
        st, logits = step(st)          # compile + warm
        jax.block_until_ready(logits)
        t0 = time.perf_counter()
        for _ in range(iters):
            st, logits = step(st)
        jax.block_until_ready(logits)
        return (time.perf_counter() - t0) / iters * 1e3

    ms_gather = run(lambda st: dp.decode_step_paged(params, st, cfg))
    ms_ragged = run(lambda st: dp.decode_step_paged_ragged(
        params, st, cfg, bound, on_tpu))
    return {
        "batch": batch,
        "lengths": list(map(int, lengths)),
        "pages_bound": bound,
        "max_pages_per_seq": MP,
        "impl": "kernel" if on_tpu else "reference",
        "ms_per_step_gather": round(ms_gather, 4),
        "ms_per_step_ragged": round(ms_ragged, 4),
        "speedup": round(ms_gather / max(ms_ragged, 1e-9), 3),
    }


# ---------------------------------------------------------------- measure


def _measure(platform: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama_config, transformer

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg_kw = dict(vocab_size=32000, max_seq_len=2048, d_model=2048,
                      n_layers=8, n_heads=16, n_kv_heads=8, d_ff=8192,
                      dtype=jnp.bfloat16, remat=False)
        page_size, prompt_len, gen_len, conc = 64, 512, 128, 8
        rates, open_duration_s = [2.0, 4.0, 8.0], 10.0
        n_ab = 2 * conc
    else:
        cfg_kw = dict(vocab_size=512, max_seq_len=256, d_model=128,
                      n_layers=2, n_heads=4, n_kv_heads=4, d_ff=256,
                      dtype=jnp.float32, remat=False)
        page_size, prompt_len, gen_len, conc = 32, 64, 32, 8
        rates, open_duration_s = [4.0, 8.0, 16.0], 6.0
        n_ab = 6 * conc

    cfg = llama_config("tiny", **cfg_kw)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [[int(x) for x in rng.integers(
        1, cfg_kw["vocab_size"] - 1, size=prompt_len)] for _ in range(16)]
    stack_kw = dict(page_size=page_size, max_slots=conc,
                    max_len=cfg_kw["max_seq_len"],
                    min_bucket=max(32, page_size))
    results: dict = {"backend": jax.default_backend(),
                     "page_size": page_size, "prompt_len": prompt_len,
                     "gen_len": gen_len}

    pd = _PDStack(cfg, params, prefill_batch_max=conc, **stack_kw)
    mono = _MonoStack(cfg, params, **stack_kw)
    try:
        # warmup both stacks (prefill + decode compiles) and check the
        # disaggregated path is token-exact against the monolithic engine
        exact = pd.generate(prompts[0], gen_len) == mono.generate(
            prompts[0], gen_len)
        results["pd_token_exact"] = bool(exact)
        # warm the coalescer's padded batch shapes (1/2/4 rows): the A/B
        # round must measure the steady state, not three compiles
        from ray_tpu.models import decoding as _dec

        bucket = len(prompts[0])
        b = 1
        while b <= conc:
            jax.block_until_ready(_dec.prefill_batch(
                params, jnp.zeros((b, bucket), jnp.int32),
                jnp.ones((b,), jnp.int32), cfg)[0])
            b *= 2

        # ---- A/B: closed loop at concurrency `conc`, interleaved -------
        # five alternating rounds per stack, median (by tokens/s) kept:
        # single ~0.3s rounds on a busy box swing +-10%, which is larger
        # than the effect under test
        rounds: dict = {"pd": [], "monolithic": []}
        for _rnd in range(5):
            for name, stack in (("pd", pd), ("monolithic", mono)):
                pre = _phase_totals()
                r = _closed_loop(stack, prompts, concurrency=conc,
                                 n_requests=n_ab, max_tokens=gen_len)
                # per-phase attribution for BOTH stacks (admission wait +
                # inter-token for monolithic; + transfer waits for PD), so
                # a future regression attributes to the right engine
                r["phase_breakdown"] = _phase_breakdown(
                    pre, _phase_totals(), n_ab)
                rounds[name].append(r)
        ab = {name: sorted(rs, key=lambda r: r["tokens_per_s"])[len(rs) // 2]
              for name, rs in rounds.items()}
        ab["rounds_per_stack"] = 5
        # top-level copy kept: the capture pipeline and the PR 11
        # attribution docs key on this location
        results["phase_breakdown"] = ab["pd"]["phase_breakdown"]
        ab["ttft_p50_speedup"] = round(
            ab["monolithic"]["p50_ttft_ms"]
            / max(ab["pd"]["p50_ttft_ms"], 1e-6), 3)
        ab["tokens_per_s_ratio"] = round(
            ab["pd"]["tokens_per_s"]
            / max(ab["monolithic"]["tokens_per_s"], 1e-9), 3)
        results["ab"] = ab

        # ---- arrival-rate sweep: open loop on the PD stack -------------
        sweep = []
        arrival_rng = np.random.default_rng(1)
        for rate in rates:
            sweep.append(_open_loop(pd, prompts, rate_rps=rate,
                                    duration_s=open_duration_s,
                                    max_tokens=gen_len, rng=arrival_rng))
        results["arrival_sweep"] = sweep

        # ---- overload row: ~3x capacity against bounded admission ------
        # capacity = the stack's measured closed-loop completion rate; at
        # 3x offered load the bounded gate sheds the excess fast and the
        # admitted requests' p99 TTFT stays near the closed-loop value
        # (the ISSUE 16 overload-shedding acceptance row)
        capacity_rps = ab["pd"]["requests"] / max(ab["pd"]["wall_s"], 1e-9)
        results["overload"] = _overload_round(
            pd, prompts, capacity_rps=capacity_rps, factor=3.0,
            duration_s=open_duration_s, max_tokens=gen_len,
            max_ongoing=conc, max_queued=conc,
            rng=np.random.default_rng(2))
    finally:
        pd.shutdown()
        mono.shutdown()

    # ---- decode-step microbench: ragged vs gather-per-slot ------------
    if on_tpu:
        ds_kw = dict(page_size=64, max_len=2048, batch=8,
                     lengths=[130, 260, 390, 140, 520, 180, 300, 450])
    else:
        ds_kw = dict(page_size=32, max_len=512, batch=8,
                     lengths=[40, 33, 60, 45, 90, 38, 75, 64])
    results["decode_step"] = _decode_step_bench(cfg, params, **ds_kw)
    results["config"] = {k: str(v) for k, v in cfg_kw.items()}
    return results


def main():
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import _capture

    child = os.environ.get("RAY_TPU_LLM_LOAD_BENCH_CHILD")
    if child:
        _capture.child_guard("RAY_TPU_LLM_LOAD_BENCH_CHILD", child)
        _capture.emit(_measure(child))
        return 0

    out = _capture.orchestrate(
        os.path.abspath(__file__), "RAY_TPU_LLM_LOAD_BENCH_CHILD",
        _BUDGET_S, _LKG_PATH,
        ["ab", "arrival_sweep", "pd_token_exact", "phase_breakdown",
         "decode_step", "overload"],
        _ROOT)
    # merge INTO LLM_BENCH.json as the `pd` section — the serving bench
    # owns the file's top level and preserves this key on rewrite
    path = os.path.join(_ROOT, "LLM_BENCH.json")
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    data["pd"] = out
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
