"""TTFT with vs without prefix caching on a repeated-prefix workload.

Workload: N requests sharing one long prompt prefix with short distinct
tails (the serve prefix router's steady state). Measures time-to-first-token
per request after a warmup request populates the cache / compilations.
Updates LLM_MICROBENCH.json with the prefix-cache rows
(LLM_BENCH.json is owned by llm_serving_bench.py, flat schema).
"""

from __future__ import annotations

import json
import os
import statistics
import time

# force CPU unless explicitly pointed at real hardware: the host env may
# preset a TPU platform this standalone process can't (and shouldn't) grab
if os.environ.get("JAX_PLATFORMS") != "tpu":
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from ray_tpu.llm import SamplingParams, TPUEngine  # noqa: E402
from ray_tpu.models import transformer  # noqa: E402
from ray_tpu.models.transformer import TransformerConfig  # noqa: E402

CFG = dict(vocab_size=512, d_model=128, n_layers=4, n_heads=8, n_kv_heads=4,
           d_ff=256, max_seq_len=1024, dtype=jnp.float32, remat=False)
PAGE = 32
PREFIX_LEN = 768      # the shared system prompt / few-shot block
N_REQUESTS = 8


def measure(enable_cache: bool, cfg, params) -> list[float]:
    eng = TPUEngine(cfg, params, max_slots=4, max_len=1024, min_bucket=32,
                    kv_layout="paged", page_size=PAGE,
                    enable_prefix_cache=enable_cache)
    rng = np.random.default_rng(0)
    prefix = [int(x) for x in rng.integers(1, 500, size=PREFIX_LEN)]
    try:
        # warmup: populates compilations and (if enabled) the cache
        list(eng.stream(prefix + [1, 2, 3],
                        SamplingParams(max_tokens=2, temperature=0.0)))
        ttfts = []
        for i in range(N_REQUESTS):
            tail = [int(x) for x in rng.integers(1, 500, size=5)]
            t0 = time.perf_counter()
            req = eng.submit(prefix + tail,
                             SamplingParams(max_tokens=2, temperature=0.0))
            first = req.out_queue.get()  # first token or sentinel
            ttfts.append((time.perf_counter() - t0) * 1e3)
        return ttfts
    finally:
        eng.shutdown()


def main():
    cfg = TransformerConfig(**CFG)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    base = measure(False, cfg, params)
    cached = measure(True, cfg, params)
    p50_base = statistics.median(base)
    p50_cached = statistics.median(cached)
    speedup = p50_base / p50_cached if p50_cached else float("inf")
    rows = [
        {"name": "prefix_ttft_ms_p50_no_cache", "value": round(p50_base, 2)},
        {"name": "prefix_ttft_ms_p50_cached", "value": round(p50_cached, 2)},
        {"name": "prefix_ttft_speedup", "value": round(speedup, 2)},
    ]
    print(json.dumps({"prefix_workload": {
        "prefix_len": PREFIX_LEN, "page_size": PAGE,
        "backend": jax.default_backend()}, "results": rows}))
    path = os.path.join(os.path.dirname(__file__), "..", "LLM_MICROBENCH.json")
    try:
        doc = json.load(open(path))
        keep = [r for r in doc.get("results", [])
                if not r["name"].startswith("prefix_ttft") and
                r["name"] != "prefix_ttft_speedup"]
        doc["results"] = keep + rows
        doc["prefix_workload"] = {"prefix_len": PREFIX_LEN,
                                  "page_size": PAGE,
                                  "backend": jax.default_backend()}
        json.dump(doc, open(path, "w"), indent=1)
    except FileNotFoundError:
        pass


if __name__ == "__main__":
    main()
