"""On-chip LLM serving benchmark: TTFT, decode throughput, concurrency,
prefix-cache and speculative variants — the serve/LLM counterpart of
bench.py (north-star row in BASELINE.md: "Serve req/s + p50 TTFT").

(reference: python/ray/serve/_private/benchmarks/ + release/llm_tests/ —
the serving suites the release pipeline gates on.)

Writes LLM_BENCH.json with an explicit ``backend`` field. Capture
hardening identical to bench.py: the TPU measurement runs in a child
whose backend init is bounded by a SELF-terminating alarm (never killed
from outside — SIGKILL mid-grant wedges the shared pool), a CPU child
still records the workload shape when the chip is unavailable, and the
last-known-good TPU result is cached across invocations.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time

_LKG_PATH = "/tmp/ray_tpu_llm_bench_last_good.json"
_BUDGET_S = float(os.environ.get("RAY_TPU_LLM_BENCH_BUDGET_S", "540"))
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)  # children run with benchmarks/ as sys.path[0]


def _build(cfg_kw: dict, engine_kw: dict):
    import jax

    from ray_tpu.llm.engine import TPUEngine
    from ray_tpu.models import llama_config, transformer

    cfg = llama_config("tiny", **cfg_kw)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    return cfg, params, TPUEngine(cfg, params, **engine_kw)


def _measure(platform: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm.engine import SamplingParams, TPUEngine
    from ray_tpu.models import transformer

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # serving-shaped decoder: wide like the train bench (MXU-friendly),
        # shorter stack so 8 concurrent 1k contexts fit HBM comfortably
        cfg_kw = dict(vocab_size=32000, max_seq_len=2048, d_model=2048,
                      n_layers=8, n_heads=16, n_kv_heads=8, d_ff=8192,
                      dtype=jnp.bfloat16, remat=False)
        prompt_len, gen_len, conc = 512, 128, 8
        prefix_len = 768
    else:
        cfg_kw = dict(vocab_size=512, max_seq_len=1024, d_model=128,
                      n_layers=2, n_heads=4, n_kv_heads=4, d_ff=256,
                      dtype=jnp.float32, remat=False)
        prompt_len, gen_len, conc = 64, 16, 4
        prefix_len = 256

    rng = np.random.default_rng(0)
    sp = SamplingParams(max_tokens=gen_len, temperature=0.0)
    results: dict = {"backend": jax.default_backend()}

    def prompt(n):
        return [int(x) for x in rng.integers(1, cfg_kw["vocab_size"] - 1,
                                             size=n)]

    # ---- base engine: TTFT + single-stream + aggregate ------------------
    cfg, params, eng = _build(cfg_kw, dict(max_slots=conc,
                                           max_len=cfg_kw["max_seq_len"],
                                           kv_layout="slot"))
    try:
        list(eng.stream(prompt(prompt_len), sp))  # compile warmup

        # TTFT p50 over 8 fresh single requests
        ttfts = []
        for _ in range(8):
            t0 = time.perf_counter()
            req = eng.submit(prompt(prompt_len), sp)
            req.out_queue.get()
            ttfts.append((time.perf_counter() - t0) * 1e3)
            for _tok in req:  # drain
                pass
        results["ttft_ms_p50"] = round(statistics.median(ttfts), 2)

        # single-stream decode tok/s (excluding prefill: time the tail)
        req = eng.submit(prompt(prompt_len), sp)
        req.out_queue.get()
        t0 = time.perf_counter()
        n = sum(1 for _ in req)
        results["decode_tokens_per_s_single"] = round(
            n / (time.perf_counter() - t0), 1)

        # aggregate decode at concurrency `conc` (continuous batching):
        # submit from threads like a serve replica pool would
        done = []
        lock = threading.Lock()

        def client(i):
            toks = list(eng.stream(prompt(prompt_len), sp))
            with lock:
                done.append(len(toks))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(conc * 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        results["aggregate_tokens_per_s"] = round(sum(done) / wall, 1)
        results["aggregate_concurrency"] = conc
        results["aggregate_requests"] = len(done)
    finally:
        eng.shutdown()

    # ---- prefix-cache variant ------------------------------------------
    def ttft_with_cache(enable: bool) -> float:
        _, _, e2 = _build(
            dict(cfg_kw),
            dict(max_slots=4, max_len=cfg_kw["max_seq_len"],
                 kv_layout="paged", page_size=32,
                 enable_prefix_cache=enable))
        try:
            shared = prompt(prefix_len)
            list(e2.stream(shared + prompt(4), SamplingParams(max_tokens=2)))
            vals = []
            for _ in range(4):
                t0 = time.perf_counter()
                req = e2.submit(shared + prompt(4),
                                SamplingParams(max_tokens=2))
                req.out_queue.get()
                vals.append((time.perf_counter() - t0) * 1e3)
                for _tok in req:
                    pass
            return statistics.median(vals)
        finally:
            e2.shutdown()

    cold = ttft_with_cache(False)
    hot = ttft_with_cache(True)
    results["prefix_ttft_ms_p50_no_cache"] = round(cold, 2)
    results["prefix_ttft_ms_p50_cached"] = round(hot, 2)
    results["prefix_ttft_speedup"] = round(cold / max(hot, 1e-6), 2)

    # ---- speculative variant (n-gram prompt lookup) --------------------
    # repetitive prompt: the regime speculation exploits — built ONCE so
    # both variants decode the identical sequence (token-exactness check)
    _spec_base = prompt(32)
    spec_prompt = (_spec_base * ((prompt_len // 32) + 1))[:prompt_len]

    # generation must be LONG enough for greedy decode to settle into a
    # repetition loop the n-gram drafter can exploit (spec_bench.py's
    # regime) — a short tail from random weights measures ~0 acceptance
    # and reads as a speculation regression when it's workload design
    spec_sp = SamplingParams(max_tokens=max(256, gen_len), temperature=0.0)

    def decode_rate(spec_k: int) -> tuple[float, list, dict]:
        _, _, e3 = _build(
            dict(cfg_kw),
            dict(max_slots=2, max_len=cfg_kw["max_seq_len"],
                 kv_layout="slot", speculative_k=spec_k))
        try:
            p = spec_prompt
            list(e3.stream(p, spec_sp))
            req = e3.submit(p, spec_sp)
            req.out_queue.get()
            t0 = time.perf_counter()
            toks = [t for t in req]
            rate = len(toks) / (time.perf_counter() - t0)
            stats = (e3.stats() or {}).get("speculative") or {}
            return rate, toks, {
                "tokens_per_step": round(stats.get("tokens_per_step", 0.0), 3),
                "acceptance_rate": round(stats.get("acceptance_rate", 0.0), 3),
            }
        finally:
            e3.shutdown()

    plain, toks_plain, _ = decode_rate(0)
    spec, toks_spec, spec_stats = decode_rate(4)
    results["speculative"] = {
        "k": 4,
        "decode_tokens_per_s_plain": round(plain, 1),
        "decode_tokens_per_s_speculative": round(spec, 1),
        "wall_speedup": round(spec / max(plain, 1e-9), 3),
        # the diagnosability pair (spec_bench.py, PERF.md): low acceptance
        # vs per-step overhead are different failure modes
        "tokens_per_step": spec_stats.get("tokens_per_step"),
        "acceptance_rate": spec_stats.get("acceptance_rate"),
        "outputs_token_exact": toks_plain == toks_spec,
    }
    # ---- instrumentation overhead: interleaved A/B rounds ---------------
    # Same protocol as dag_bench._alternating_overhead: alternate
    # instrumented (default RayConfig.serve_metrics + span sampling) and
    # uninstrumented rounds in ONE session, rebuilding the engine per round
    # so the construction-time knob read takes effect; interleaving cancels
    # scheduling drift. Budget: ≤5% median per-request latency (ISSUE 11).
    from ray_tpu._private.ray_config import RayConfig

    def serving_round(n_requests: int) -> list:
        # Measured path = the engine's per-token instrumentation
        # (admission_wait + inter_token observes, the dominant hot-path
        # cost) PLUS the per-request request-path surface driven exactly
        # as the proxy/handle/replica drive it — phase observes, the
        # sampling tick, and the flight-recorder append. All of it
        # self-gates on the same knobs, so the off mode measures the true
        # uninstrumented baseline.
        from ray_tpu.serve import request_context as rc

        e4 = TPUEngine(cfg, params, max_slots=conc,
                       max_len=cfg_kw["max_seq_len"], kv_layout="paged",
                       page_size=32)
        try:
            list(e4.stream(prompt(prompt_len), sp))  # jit-cache warm
            lats = []
            for i in range(n_requests):
                t0 = time.perf_counter()
                rec = {"request_id": rc.new_request_id(),
                       "component": "bench", "sampled": rc.sample_request()}
                for phase in ("accept", "parse", "route"):
                    rc.observe_phase(rc.PROXY_PHASE, phase, 1e-6, rec)
                rc.observe_phase(rc.HANDLE_PHASE, "pick", 1e-6, rec)
                rc.observe_phase(rc.REPLICA_PHASE, "queue_wait", 1e-6, rec)
                list(e4.stream(prompt(prompt_len), sp))
                rc.observe_phase(rc.REPLICA_PHASE, "execute",
                                 time.perf_counter() - t0, rec)
                rc.observe_phase(rc.HANDLE_PHASE, "rtt",
                                 time.perf_counter() - t0, rec)
                rc.record_request(rec, t0, status=200)
                lats.append(time.perf_counter() - t0)
            return lats
        finally:
            e4.shutdown()

    knobs = ("RAY_TPU_SERVE_METRICS", "RAY_TPU_SERVE_SPAN_SAMPLE_EVERY")
    saved = {k: os.environ.get(k) for k in knobs}
    samples: dict = {"on": [], "off": []}
    try:
        for _ in range(3):
            for mode in ("on", "off"):
                if mode == "off":
                    os.environ["RAY_TPU_SERVE_METRICS"] = "0"
                    os.environ["RAY_TPU_SERVE_SPAN_SAMPLE_EVERY"] = "0"
                else:
                    # FORCE defaults (pop ambient overrides): a shell
                    # exporting RAY_TPU_SERVE_METRICS=0 must not turn the
                    # comparison into off-vs-off
                    for k in knobs:
                        os.environ.pop(k, None)
                RayConfig.reset()
                samples[mode].extend(serving_round(4))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        RayConfig.reset()
    med_on = statistics.median(samples["on"])
    med_off = statistics.median(samples["off"])
    overhead_pct = (med_on / max(med_off, 1e-9) - 1.0) * 100.0
    results["instrumentation_ab"] = {
        "median_request_ms_instrumented": round(med_on * 1e3, 3),
        "median_request_ms_uninstrumented": round(med_off * 1e3, 3),
        "overhead_pct": round(overhead_pct, 2),
        "budget_pct": 5.0,
        "within_budget": bool(overhead_pct <= 5.0),
        "requests_per_mode": len(samples["on"]),
    }

    results["config"] = {k: str(v) for k, v in cfg_kw.items()}
    results["prompt_len"] = prompt_len
    results["gen_len"] = gen_len
    return results


def main():
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import _capture

    child = os.environ.get("RAY_TPU_LLM_BENCH_CHILD")
    if child:
        _capture.child_guard("RAY_TPU_LLM_BENCH_CHILD", child)
        _capture.emit(_measure(child))
        return 0

    out = _capture.orchestrate(
        os.path.abspath(__file__), "RAY_TPU_LLM_BENCH_CHILD", _BUDGET_S,
        _LKG_PATH,
        ["ttft_ms_p50", "decode_tokens_per_s_single",
         "aggregate_tokens_per_s", "instrumentation_ab"],
        _ROOT)
    path = os.path.join(_ROOT, "LLM_BENCH.json")
    try:  # sections owned by OTHER benches (llm_load_bench's `pd`, future
        #   additions): keep every prior key this run didn't produce
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = {}
    for k, v in prior.items():
        out.setdefault(k, v)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
