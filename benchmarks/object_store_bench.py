"""Object-store backend shootout: native shm arena vs file-per-object.

The arena (cpp/shm_store.cc) is the default object plane as of the flip in
ray_tpu/_private/object_store.py; this bench keeps the decision honest by
recording, for BOTH backends:

  - put/get latency medians at 1 KiB / 64 KiB / 4 MiB
  - sustained put throughput over a 10k-object run
  - tmpfs inode count after that run (the arena must hold O(1) segments
    while the file backend burns one inode per object)

Rows land in MICROBENCH.json as `object_store_*_{arena,file}` like the
other benches. Store-level measurement (no session) so the numbers isolate
the storage plane from GCS/serialization costs.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

SIZES = {"1KiB": 1 << 10, "64KiB": 1 << 16, "4MiB": 4 << 20}
ITERS = {"1KiB": 2000, "64KiB": 500, "4MiB": 50}
SUSTAINED_N = 10_000
SUSTAINED_SIZE = 16 << 10


def _make_store(backend: str, ns: str):
    if backend == "arena":
        from ray_tpu._private.shm_arena import ArenaStore

        # room for every latency-phase object plus the sustained run, so
        # eviction/spill cost never pollutes the latency medians
        return ArenaStore(ns, capacity=2 << 30)
    from ray_tpu._private.object_store import ShmObjectStore

    return ShmObjectStore(ns)


def _tmpfs_inodes(ns: str) -> int:
    prefix = f"rtpu_{ns}_"
    return sum(1 for n in os.listdir("/dev/shm") if n.startswith(prefix))


def bench_backend(backend: str) -> dict:
    ns = f"osbench{backend}"
    store = _make_store(backend, ns)
    out: dict = {}
    try:
        for tag, size in SIZES.items():
            payload = os.urandom(size)
            n = ITERS[tag]
            puts = []
            for i in range(n):
                oid = f"{tag}{i:08d}".lower()
                t0 = time.perf_counter()
                store.put_parts(oid, [payload], size)
                puts.append(time.perf_counter() - t0)
            gets = []
            for i in range(n):
                oid = f"{tag}{i:08d}".lower()
                t0 = time.perf_counter()
                obj = store.get(oid)
                assert obj.buf[:8] == payload[:8]
                if hasattr(obj, "release"):
                    obj.release()
                gets.append(time.perf_counter() - t0)
            out[f"put_{tag}"] = statistics.median(puts) * 1e6
            out[f"get_{tag}"] = statistics.median(gets) * 1e6
        # sustained put: 10k distinct objects back to back; the inode row
        # is the DELTA this run added to tmpfs (arena: 0 — objects land
        # inside the one pre-existing segment; file: one per object)
        payload = os.urandom(SUSTAINED_SIZE)
        inodes_before = _tmpfs_inodes(ns)
        t0 = time.perf_counter()
        for i in range(SUSTAINED_N):
            store.put_parts(f"sus{i:08d}", [payload], SUSTAINED_SIZE)
        dt = time.perf_counter() - t0
        out["sustained_put_per_s"] = SUSTAINED_N / dt
        out["sustained_put_mib_per_s"] = SUSTAINED_N * SUSTAINED_SIZE / dt / (1 << 20)
        out["tmpfs_inodes_10k"] = _tmpfs_inodes(ns) - inodes_before
    finally:
        store.cleanup_session()
    return out


def main():
    results: dict = {}
    for backend in ("file", "arena"):
        for k, v in bench_backend(backend).items():
            results[f"object_store_{k}_{backend}"] = round(v, 2)
    print(json.dumps(results, indent=1))
    from ray_tpu._private.ray_perf import merge_microbench

    rows = []
    for name, v in results.items():
        if "_per_s" in name:
            rows.append({"name": name, "ops_per_s": v, "value": None,
                         "us_per_op": None})
        elif name.startswith(("object_store_put_", "object_store_get_")):
            rows.append({"name": name, "ops_per_s": None, "value": None,
                         "us_per_op": v})
        else:
            rows.append({"name": name, "ops_per_s": None, "value": v,
                         "us_per_op": None})
    merge_microbench(os.path.join(os.path.dirname(__file__), "..",
                                  "MICROBENCH.json"), rows)


if __name__ == "__main__":
    main()
