"""Scheduler scalability microbench: deep-queue submission + shallow drain.

Reference envelope: 1M queued tasks on a single node
(/root/reference/release/benchmarks/README.md:29 "single_node
... 10k queued tasks" and distributed 1M queued). The deep-queue case
measures submit throughput while every worker is blocked and the pending
queue is already deep — the round-2 fix got 93→296/s; round 4 shards the
pending queue by resource shape so per-event feasibility is a dict probe.

Usage: python benchmarks/sched_bench.py [--deep N] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def bench_deep_queue(n_deep: int = 20000) -> dict:
    # measure the GCS scheduler path, not the caller-local direct queue
    os.environ.setdefault("RAY_TPU_DIRECT_DISPATCH", "0")
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_workers=2, max_workers=2)
    release = threading.Event()

    @ray_tpu.remote
    def blocker(path):
        import time as _t
        open(path, "w").close()
        while not os.path.exists(path + ".go"):
            _t.sleep(0.05)
        return "unblocked"

    @ray_tpu.remote
    def noop():
        return 0

    import tempfile
    d = tempfile.mkdtemp(prefix="schedbench")
    marks = [os.path.join(d, f"b{i}") for i in range(2)]
    blockers = [blocker.remote(m) for m in marks]
    deadline = time.time() + 30
    while not all(os.path.exists(m) for m in marks):
        if time.time() > deadline:
            raise RuntimeError("blockers never started")
        time.sleep(0.05)

    # deep-queue submission: every submit lands behind blocked workers
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n_deep)]
    t_submit = time.perf_counter() - t0
    submit_rate = n_deep / t_submit

    # drain: unblock and wait for everything
    t1 = time.perf_counter()
    for m in marks:
        open(m + ".go", "w").close()
    ray_tpu.get(blockers)
    ray_tpu.get(refs)
    t_drain = time.perf_counter() - t1
    drain_rate = n_deep / t_drain

    ray_tpu.shutdown()
    return {
        "deep_queue_n": n_deep,
        "deep_queue_submit_per_s": round(submit_rate, 1),
        "deep_queue_drain_per_s": round(drain_rate, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--deep", type=int, default=20000)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    out = bench_deep_queue(args.deep)
    print(json.dumps(out) if args.json else out)


if __name__ == "__main__":
    main()
