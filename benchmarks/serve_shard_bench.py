"""Sharded proxy-plane benchmark — `python benchmarks/serve_shard_bench.py`.

Measures the SAME noop HTTP rows as ray_tpu.scripts.serve_bench but through
the sharded proxy plane (N workers accepting on one SO_REUSEPORT port,
routing from the controller's shm broadcast), plus a large-payload row that
exercises the zero-copy body/response path (bodies and byte results above
`serve_zero_copy_threshold_bytes` ride the arena object plane as refs, not
pickled payloads). Results land in the ``sharded`` section of
SERVE_BENCH.json via the section-preserving merge writer, next to (never
clobbering) serve_bench's single-proxy ``results`` baseline; the per-phase
proxy histograms (`ray_tpu_serve_proxy_phase_seconds`) are summarized into
the row so the win/loss is attributable.

Env knobs: RAY_TPU_SHARD_BENCH_PROXIES (default 2),
RAY_TPU_SHARD_BENCH_N (sequential reqs, default 300).
"""

from __future__ import annotations

import http.client
import json
import os
import sys
import threading
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)


def _phase_summary(snap: dict, name: str) -> dict:
    """{phase: {count, total_s}} summed across sources for one histogram."""
    rec = snap.get(name)
    if not rec:
        return {}
    out: dict = {}
    for series in rec.get("series", {}).values():
        for tags, st in series:
            phase = dict(tuple(t) for t in tags).get("phase", "?")
            agg = out.setdefault(phase, {"count": 0, "total_s": 0.0})
            agg["count"] += int(st.get("count", 0))
            agg["total_s"] = round(agg["total_s"] + st.get("sum", 0.0), 4)
    return out


def main() -> int:
    import ray_tpu
    from ray_tpu import serve

    num_proxies = int(os.environ.get("RAY_TPU_SHARD_BENCH_PROXIES", "2"))
    N = int(os.environ.get("RAY_TPU_SHARD_BENCH_N", "300"))

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=32, num_workers=2, max_workers=10)
    rows = []

    @serve.deployment(num_replicas=2, max_ongoing_requests=32)
    def noop(req):
        return {"ok": True}

    @serve.deployment(num_replicas=1, max_ongoing_requests=8)
    def blob(req):
        # byte result above the zero-copy threshold: rides the object
        # plane back as a result_ref, served as application/octet-stream
        n = int((req.get("body") or {}).get("n") or (1 << 20))
        return b"y" * n

    serve.run(noop.bind(), name="noop", route_prefix="/noop")
    serve.run(blob.bind(), name="blob", route_prefix="/blob")
    serve.start(http_port=0, num_proxies=num_proxies)
    host, port = serve.http_address()
    st = serve.proxy_status()
    print(f"proxy plane: {st['num_proxies']} shards on {host}:{port} "
          f"({st['mode']})")

    def req(conn, path, body):
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        return r.status, r.read()

    warm = http.client.HTTPConnection(host, port, timeout=30)
    assert req(warm, "/noop", b"{}")[0] == 200
    warm.close()

    # sequential noop latency over one keep-alive connection
    conn = http.client.HTTPConnection(host, port, timeout=30)
    t0 = time.perf_counter()
    for _ in range(N):
        req(conn, "/noop", b"{}")
    dt = (time.perf_counter() - t0) / N
    conn.close()
    rows.append({"name": "http_noop_sequential_sharded",
                 "ops_per_s": round(1 / dt, 1),
                 "us_per_op": round(dt * 1e6, 1)})
    print(f"http_noop_sequential_sharded: {1/dt:,.0f} req/s")

    # concurrent noop throughput (16 client threads, keep-alive each)
    CT, PER = 16, 60
    done: list = []

    def worker():
        c = http.client.HTTPConnection(host, port, timeout=30)
        n = sum(1 for _ in range(PER) if req(c, "/noop", b"{}")[0] == 200)
        c.close()
        done.append(n)

    threads = [threading.Thread(target=worker) for _ in range(CT)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ok = sum(done)
    assert ok == CT * PER, f"dropped requests: {ok}/{CT * PER}"
    rows.append({"name": "http_noop_concurrent16_sharded",
                 "ops_per_s": round(ok / wall, 1),
                 "us_per_op": round(wall / max(ok, 1) * 1e6, 1)})
    print(f"http_noop_concurrent16_sharded: {ok/wall:,.0f} req/s ({ok} ok)")

    # zero-copy payload row: ~1 MiB JSON body up, 1 MiB bytes back — both
    # legs above the threshold, so neither moves as a pickled RPC payload
    MB = 1 << 20
    big_body = json.dumps({"n": MB, "pad": "x" * MB}).encode()
    conn = http.client.HTTPConnection(host, port, timeout=60)
    BN = 30
    t0 = time.perf_counter()
    for _ in range(BN):
        status, payload = req(conn, "/blob", big_body)
        assert status == 200 and len(payload) == MB, (status, len(payload))
    bdt = (time.perf_counter() - t0) / BN
    conn.close()
    mb_per_s = (len(big_body) + MB) / MB / bdt
    rows.append({"name": "http_zero_copy_1mib_roundtrip",
                 "ops_per_s": round(1 / bdt, 1),
                 "mb_per_s": round(mb_per_s, 1),
                 "us_per_op": round(bdt * 1e6, 1)})
    print(f"http_zero_copy_1mib_roundtrip: {1/bdt:,.1f} req/s "
          f"({mb_per_s:,.0f} MB/s)")

    # phase attribution + plane gauges from the GCS aggregate (shard phase
    # observes arrive batched, on the telemetry flush interval)
    time.sleep(1.5)
    from ray_tpu._private.api import _get_worker

    snap = _get_worker().rpc({"type": "metrics_snapshot"})["metrics"]
    phases = _phase_summary(snap, "ray_tpu_serve_proxy_phase_seconds")

    # speedup vs the single-proxy baseline already in the artifact
    baseline = {}
    try:
        with open(os.path.join(_ROOT, "SERVE_BENCH.json")) as f:
            baseline = {r["name"]: r["ops_per_s"]
                        for r in json.load(f).get("results", [])}
    except (OSError, ValueError, KeyError):
        pass
    base = baseline.get("http_noop_concurrent16")
    speedup = (round(rows[1]["ops_per_s"] / base, 2) if base else None)

    serve.shutdown()
    ray_tpu.shutdown()

    from ray_tpu.scripts._artifacts import merge_artifact

    payload = {
        "num_proxies": num_proxies,
        "cpus": os.cpu_count(),
        "rows": rows,
        "speedup_vs_single_proxy_concurrent16": speedup,
        "proxy_phase_seconds": phases,
    }
    if (os.cpu_count() or 1) <= 2:
        # shards contend for the same core(s): the row proves the plane
        # costs ~nothing at parity, NOT the multi-core scale-out it exists
        # for — rerun on a >=8-core host for the ingress-scaling number
        payload["note"] = (f"{os.cpu_count()}-core host: shards serialize "
                           "on the CPU; expect ~linear ingress scaling only "
                           "with cores to spread across")
    print("wrote", merge_artifact("SERVE_BENCH.json", "sharded", payload))
    print(json.dumps(payload, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
