"""Speculative-decoding microbenchmark: tokens/step and wall-clock speedup
of n-gram speculation vs plain decode on a repetitive workload.

Appends a `speculative` section to LLM_MICROBENCH.json
(LLM_BENCH.json is owned by llm_serving_bench.py, flat schema). CPU numbers are
relative (the verify-step cost ratio differs on the MXU, in speculation's
favor — decode is memory-bound there).

Usage (the env prefix is REQUIRED — sitecustomize pre-imports jax at
interpreter start, so in-script environ changes are too late):
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python benchmarks/spec_bench.py [--tokens N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# hard-set: the host env PRESETS JAX_PLATFORMS to the TPU platform; this
# relative benchmark runs on CPU and must never dial the shared device pool
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tokens", type=int, default=256)
    ap.add_argument("--k", type=int, default=4)
    args = ap.parse_args()

    from ray_tpu.llm import SamplingParams, TPUEngine
    from ray_tpu.models import transformer
    from ray_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq_len=1024, dtype=jnp.float32, remat=False)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    # repetitive prompt: the regime speculation targets (templated text,
    # code, summarization-with-copying)
    prompt = [7, 3, 9, 4] * 8

    def run(spec_k: int):
        eng = TPUEngine(cfg, params, max_slots=2, max_len=1024,
                        min_bucket=32, speculative_k=spec_k)
        sp = SamplingParams(max_tokens=args.tokens, temperature=0.0)
        out = eng.generate(prompt, sp)  # warmup/compile
        t0 = time.perf_counter()
        out = eng.generate(prompt, sp)
        dt = time.perf_counter() - t0
        stats = eng.stats().get("speculative", {})
        eng.shutdown()
        return len(out) / dt, stats, out

    plain_tps, _, out_a = run(0)
    spec_tps, stats, out_b = run(args.k)
    assert out_a == out_b, "speculative output diverged from plain decode"

    section = {
        "k": args.k,
        "decode_tokens_per_s_plain": round(plain_tps, 1),
        "decode_tokens_per_s_speculative": round(spec_tps, 1),
        "wall_speedup": round(spec_tps / plain_tps, 3),
        "tokens_per_step": round(stats.get("tokens_per_step", 0.0), 3),
        "acceptance_rate": round(stats.get("acceptance_rate", 0.0), 3),
        "backend": jax.default_backend(),
        "outputs_token_exact": True,
    }
    print(json.dumps(section, indent=1))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "LLM_MICROBENCH.json")
    try:
        doc = json.load(open(path))
    except (OSError, ValueError):
        doc = {}
    doc["speculative"] = section
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"appended to {path}")


if __name__ == "__main__":
    main()
