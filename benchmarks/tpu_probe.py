"""Bounded out-of-process TPU health probe.

Spawns ONE child that attempts jax TPU backend init and exits by itself
(internal alarm) if the shared device pool is wedged — the child is never
SIGTERMed/SIGKILLed from outside while it may hold a grant, because killing
a process mid-grant is exactly what wedges the pool (PERF.md operational
notes, rounds 1-3).

Exit code 0 = healthy (prints device kind), 1 = unavailable.
Usage: python benchmarks/tpu_probe.py [timeout_s]
"""

from __future__ import annotations

import os
import subprocess
import sys

_CHILD = r"""
import os, signal, sys
# default SIGALRM disposition terminates at the C level — a Python handler
# could never run while the process is blocked inside jax's native backend
# init (the exact wedged-pool case this probe detects). Self-termination by
# alarm is indistinguishable from the wedge's own state for the pool (init
# never completed a grant), and it guarantees no stuck probe accumulates.
signal.alarm(max(1, int(float(sys.argv[1]))))
os.environ.pop("JAX_PLATFORMS", None)
import jax
try:
    devs = jax.devices("tpu")
except Exception:
    os._exit(1)
if not devs:
    os._exit(1)
import jax.numpy as jnp
x = jnp.ones((8, 8))
(x @ x).block_until_ready()
signal.alarm(0)  # only after the first real computation completes
print(devs[0].device_kind, flush=True)
os._exit(0)
"""


def probe(timeout_s: float = 120.0) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(int(timeout_s))],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True, env=env)
    deadline = timeout_s + 60
    try:
        out, _ = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        # NEVER kill the child: it may hold a half-complete grant, and
        # killing mid-grant is what wedges the pool. Its own SIGALRM exits
        # it eventually; we just stop waiting and report unhealthy.
        return False
    if proc.returncode == 0:
        print((out or "").strip())
        return True
    return False


if __name__ == "__main__":
    t = float(sys.argv[1]) if len(sys.argv) > 1 else 120.0
    ok = probe(t)
    print("TPU_HEALTHY" if ok else "TPU_UNAVAILABLE", flush=True)
    sys.exit(0 if ok else 1)
