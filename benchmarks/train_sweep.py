"""Full train-step sweep on the real chip: attention impl x remat policy x
shape. Each config runs in-process sequentially; prints tokens/s + 6ND MFU.

Usage: python benchmarks/train_sweep.py [config_name ...]
"""

from __future__ import annotations

import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax


def run(name, *, d_model=2048, n_layers=8, n_heads=16, n_kv_heads=8, d_ff=8192,
        batch=8, seq=2048, remat=True, remat_policy="nothing", steps=20,
        attn_impl=None, opt_kind="adamw", ce_chunk=None):
    from ray_tpu.models import llama_config, transformer

    cfg = llama_config(
        "tiny", vocab_size=32000, max_seq_len=seq, d_model=d_model,
        n_layers=n_layers, n_heads=n_heads, n_kv_heads=n_kv_heads, d_ff=d_ff,
        dtype=jnp.bfloat16, remat=remat, remat_policy=remat_policy,
    )
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    n_params = sum(math.prod(p.shape) for p in jax.tree.leaves(params))
    if opt_kind == "adamw":
        opt = optax.adamw(1e-4, weight_decay=0.01)
    elif opt_kind == "adafactor":
        opt = optax.adafactor(1e-4)
    elif opt_kind == "adamw_int8":
        from ray_tpu.train.optim import adamw_int8

        opt = adamw_int8(1e-4, weight_decay=0.01)
    else:
        raise ValueError(opt_kind)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(transformer.loss_fn)(
            params, tokens, cfg, attn_impl=attn_impl, ce_chunk=ce_chunk)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    tokens = jnp.asarray(
        np.random.randint(0, cfg.vocab_size, (batch, seq + 1), dtype=np.int32))
    failed = None
    try:
        t_c0 = time.perf_counter()
        params, opt_state, loss = step(params, opt_state, tokens)
        float(loss)
        compile_s = time.perf_counter() - t_c0
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(loss)
        dt = (time.perf_counter() - t0) / steps
    except Exception as e:
        failed = f"{type(e).__name__}: {str(e)[:200]}"
    if failed:
        # cleanup OUTSIDE the except clause: while it is live, the
        # interpreter's exception state keeps the traceback (and through
        # it this config's device buffers) alive, which would OOM every
        # subsequent config in this process
        print(f"{name}: FAILED {failed}", flush=True)
        import gc

        del params, opt_state, step
        gc.collect()
        jax.clear_caches()
        return
    tps = batch * seq / dt
    mfu = tps * 6 * n_params / 197e12
    print(f"{name}: params={n_params/1e6:.0f}M step={dt*1e3:.1f}ms "
          f"tok/s={tps:,.0f} mfu={mfu:.4f} (compile {compile_s:.0f}s)", flush=True)


CONFIGS = {
    "base_ref": dict(attn_impl="reference"),                      # round-2 bench config
    "flash": dict(),                                              # auto -> flash now
    "flash_dots": dict(remat_policy="dots"),
    "flash_noremat": dict(remat=False),
    "flash_noremat_b16": dict(remat=False, batch=16),
    "flash_b16": dict(batch=16),
    "flash_s4096": dict(seq=4096, batch=4),
    "flash_d2560": dict(d_model=2560, n_heads=20, n_kv_heads=10, d_ff=10240),
    "flash_L12": dict(n_layers=12),
    "flash_L12_dots": dict(n_layers=12, remat_policy="dots"),
    "flash_adafactor_noremat": dict(remat=False, opt_kind="adafactor"),
    # round-4 levers: int8 optimizer state frees ~4.8GB at 634M, enough to
    # relax remat. Full no-remat at b8 OOMed on hardware; dots-policy and
    # smaller-batch no-remat are the candidates.
    "int8_dots": dict(remat_policy="dots", opt_kind="adamw_int8"),
    "int8_noremat": dict(remat=False, opt_kind="adamw_int8"),
    "int8_noremat_b4": dict(remat=False, batch=4, opt_kind="adamw_int8"),
    "int8_noremat_b6": dict(remat=False, batch=6, opt_kind="adamw_int8"),
    "int8_flash": dict(opt_kind="adamw_int8"),
    "flash_b24": dict(batch=24),
    "flash_b32": dict(batch=32),
    "flash_b16_dots": dict(batch=16, remat_policy="dots"),
    "flash_b16_ce4096": dict(batch=16, ce_chunk=4096),
    "flash_b16_ce8192": dict(batch=16, ce_chunk=8192),
    # selective remat: recompute only every other layer in backward
    "flash_pairs": dict(remat_policy="pairs"),
    "flash_pairs_b12": dict(remat_policy="pairs", batch=12),
    "flash_pairs_b16": dict(remat_policy="pairs", batch=16),
}


def main():
    names = sys.argv[1:] or ["base_ref", "flash", "flash_dots", "flash_noremat"]
    print("backend:", jax.default_backend(), flush=True)
    for n in names:
        run(n, **CONFIGS[n])


if __name__ == "__main__":
    main()
