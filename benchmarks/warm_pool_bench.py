"""Cold first-task latency: on-demand spawn vs warm worker pool.

Reference behavior: prestarted pool (src/ray/raylet/worker_pool.h:280).
Prints one JSON object with both latencies.
"""

from __future__ import annotations

import json
import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def measure(warm: int) -> float:
    import importlib

    if warm:
        os.environ["RAY_TPU_WARM_POOL_SIZE"] = str(warm)
    else:
        os.environ.pop("RAY_TPU_WARM_POOL_SIZE", None)
    from ray_tpu._private.ray_config import RayConfig

    RayConfig.reset()
    import ray_tpu

    ray_tpu.init(num_cpus=4, num_workers=0, max_workers=4)

    @ray_tpu.remote
    def f():
        return 1

    if warm:
        # let the floor fill before the cold-task measurement
        from ray_tpu._private.api import _get_worker

        w = _get_worker()
        deadline = time.time() + 60
        while time.time() < deadline:
            rows = w.rpc({"type": "list_workers"}).get("workers", [])
            if sum(1 for x in rows if x.get("idle")
                   and not x.get("tpu_chips")) >= warm:
                break
            time.sleep(0.1)
    t0 = time.perf_counter()
    assert ray_tpu.get(f.remote(), timeout=60) == 1
    dt = time.perf_counter() - t0
    ray_tpu.shutdown()
    return dt


def main():
    cold_spawn = measure(0)
    warm = measure(2)
    print(json.dumps({
        "first_task_latency_spawn_ms": round(cold_spawn * 1e3, 1),
        "first_task_latency_warm_pool_ms": round(warm * 1e3, 1),
    }))


if __name__ == "__main__":
    main()
