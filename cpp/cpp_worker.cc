// C++ API worker: a native process that joins the cluster as a worker and
// executes REGISTERED C++ functions submitted from any driver.
//
// (reference capability: the C++ worker API, /root/reference/cpp/ — tasks
// target functions by NAME for cross-language calls; the reference speaks
// gRPC+protobuf, this build's control plane is a framed protocol, so the
// language-neutral encoding is JSON frames: 8-byte little-endian length,
// then "\0JSN" + UTF-8 JSON. The Python GCS auto-detects the codec per
// frame and re-encodes results for Python consumers.)
//
// Usage:  cpp_worker --address <host:port> [--node node-0] [--host host-0]
// Extend: add functions to install_functions() below (or link your own TU
// that calls ray_tpu::register_function before ray_tpu::worker_main).

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <random>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray_tpu {

// ----------------------------------------------------------------- JSON
// Minimal JSON value + parser + serializer: the subset the control plane
// uses (objects, arrays, strings, doubles/ints, bools, null).

struct Json;
using JsonArr = std::vector<Json>;
using JsonObj = std::vector<std::pair<std::string, Json>>;

struct Json {
  enum Kind { NUL, BOOL, INT, DBL, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  JsonArr arr;
  JsonObj obj;

  Json() = default;
  static Json null() { return Json(); }
  static Json of(bool v) { Json j; j.kind = BOOL; j.b = v; return j; }
  static Json of(int64_t v) { Json j; j.kind = INT; j.i = v; return j; }
  static Json of(double v) { Json j; j.kind = DBL; j.d = v; return j; }
  static Json of(const std::string& v) { Json j; j.kind = STR; j.s = v; return j; }
  static Json of(const char* v) { return of(std::string(v)); }
  static Json array(JsonArr v = {}) { Json j; j.kind = ARR; j.arr = std::move(v); return j; }
  static Json object(JsonObj v = {}) { Json j; j.kind = OBJ; j.obj = std::move(v); return j; }

  double as_number() const {
    if (kind == INT) return static_cast<double>(i);
    if (kind == DBL) return d;
    throw std::runtime_error("not a number");
  }
  const Json* get(const std::string& key) const {
    for (const auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
  void set(const std::string& key, Json v) {
    obj.emplace_back(key, std::move(v));
  }
};

struct Parser {
  const char* p;
  const char* end;
  explicit Parser(const std::string& text)
      : p(text.data()), end(text.data() + text.size()) {}

  [[noreturn]] void fail(const char* why) {
    throw std::runtime_error(std::string("json parse: ") + why);
  }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  char peek() {
    skip_ws();
    if (p >= end) fail("eof");
    return *p;
  }
  void expect(char c) {
    if (peek() != c) fail("unexpected char");
    ++p;
  }
  bool consume(char c) {
    if (p < end && peek() == c) { ++p; return true; }
    return false;
  }

  Json parse_value() {
    char c = peek();
    if (c == '{') return parse_object();
    if (c == '[') return parse_array();
    if (c == '"') return Json::of(parse_string());
    if (c == 't') { literal("true"); return Json::of(true); }
    if (c == 'f') { literal("false"); return Json::of(false); }
    if (c == 'n') { literal("null"); return Json::null(); }
    return parse_number();
  }
  void literal(const char* lit) {
    size_t n = std::strlen(lit);
    if (static_cast<size_t>(end - p) < n || std::strncmp(p, lit, n) != 0)
      fail("bad literal");
    p += n;
  }
  Json parse_object() {
    expect('{');
    Json out = Json::object();
    if (consume('}')) return out;
    while (true) {
      std::string key = parse_string();
      expect(':');
      out.obj.emplace_back(std::move(key), parse_value());
      if (consume('}')) return out;
      expect(',');
    }
  }
  Json parse_array() {
    expect('[');
    Json out = Json::array();
    if (consume(']')) return out;
    while (true) {
      out.arr.push_back(parse_value());
      if (consume(']')) return out;
      expect(',');
    }
  }
  std::string parse_string() {
    expect('"');
    std::string out;
    while (p < end) {
      char c = *p++;
      if (c == '"') return out;
      if (c != '\\') { out += c; continue; }
      if (p >= end) fail("eof in escape");
      char e = *p++;
      switch (e) {
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        case '/': out += '/'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'n': out += '\n'; break;
        case 'r': out += '\r'; break;
        case 't': out += '\t'; break;
        case 'u': {
          if (end - p < 4) fail("short \\u");
          unsigned cp = 0;
          for (int k = 0; k < 4; ++k) {
            char h = *p++;
            cp <<= 4;
            if (h >= '0' && h <= '9') cp |= h - '0';
            else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
            else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
            else fail("bad hex");
          }
          // utf-8 encode (surrogate pairs folded to replacement — the
          // control plane never sends astral identifiers)
          if (cp < 0x80) out += static_cast<char>(cp);
          else if (cp < 0x800) {
            out += static_cast<char>(0xC0 | (cp >> 6));
            out += static_cast<char>(0x80 | (cp & 0x3F));
          } else {
            out += static_cast<char>(0xE0 | (cp >> 12));
            out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
            out += static_cast<char>(0x80 | (cp & 0x3F));
          }
          break;
        }
        default: fail("bad escape");
      }
    }
    fail("eof in string");
  }
  Json parse_number() {
    const char* start = p;
    if (p < end && *p == '-') ++p;
    bool is_int = true;
    while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                       *p == 'E' || *p == '+' || *p == '-')) {
      if (*p == '.' || *p == 'e' || *p == 'E') is_int = false;
      ++p;
    }
    std::string tok(start, p - start);
    if (tok.empty()) fail("bad number");
    if (is_int) {
      try {
        return Json::of(static_cast<int64_t>(std::stoll(tok)));
      } catch (...) { /* overflow: fall through to double */ }
    }
    return Json::of(std::stod(tok));
  }
};

inline Json parse_json(const std::string& text) {
  Parser parser(text);
  Json v = parser.parse_value();
  return v;
}

inline void dump_json(const Json& v, std::string& out) {
  switch (v.kind) {
    case Json::NUL: out += "null"; break;
    case Json::BOOL: out += v.b ? "true" : "false"; break;
    case Json::INT: out += std::to_string(v.i); break;
    case Json::DBL: {
      if (std::isfinite(v.d)) {
        char buf[32];
        std::snprintf(buf, sizeof(buf), "%.17g", v.d);
        out += buf;
      } else {
        out += "null";  // JSON has no inf/nan
      }
      break;
    }
    case Json::STR: {
      out += '"';
      for (unsigned char c : v.s) {
        switch (c) {
          case '"': out += "\\\""; break;
          case '\\': out += "\\\\"; break;
          case '\n': out += "\\n"; break;
          case '\r': out += "\\r"; break;
          case '\t': out += "\\t"; break;
          default:
            if (c < 0x20) {
              char buf[8];
              std::snprintf(buf, sizeof(buf), "\\u%04x", c);
              out += buf;
            } else {
              out += static_cast<char>(c);
            }
        }
      }
      out += '"';
      break;
    }
    case Json::ARR: {
      out += '[';
      for (size_t k = 0; k < v.arr.size(); ++k) {
        if (k) out += ',';
        dump_json(v.arr[k], out);
      }
      out += ']';
      break;
    }
    case Json::OBJ: {
      out += '{';
      for (size_t k = 0; k < v.obj.size(); ++k) {
        if (k) out += ',';
        dump_json(Json::of(v.obj[k].first), out);
        out += ':';
        dump_json(v.obj[k].second, out);
      }
      out += '}';
      break;
    }
  }
}

// ------------------------------------------------------------- transport
// Frame: 8-byte little-endian length, then "\0JSN" + JSON bytes (the
// Python MsgConnection auto-detects the magic; pickles never start with
// \0, so the discriminator is unambiguous).

static const char kMagic[4] = {'\0', 'J', 'S', 'N'};

class Conn {
 public:
  explicit Conn(int fd) : fd_(fd) {}
  ~Conn() { if (fd_ >= 0) ::close(fd_); }

  void send(const Json& msg) {
    std::string payload(kMagic, 4);
    dump_json(msg, payload);
    uint64_t n = payload.size();
    char head[8];
    for (int k = 0; k < 8; ++k) head[k] = static_cast<char>((n >> (8 * k)) & 0xFF);
    write_all(head, 8);
    write_all(payload.data(), payload.size());
  }

  Json recv() {
    char head[8];
    read_all(head, 8);
    uint64_t n = 0;
    for (int k = 7; k >= 0; --k) n = (n << 8) | static_cast<unsigned char>(head[k]);
    if (n > (1ull << 30)) throw std::runtime_error("oversized frame");
    std::string payload(n, '\0');
    read_all(payload.data(), n);
    if (n < 4 || std::memcmp(payload.data(), kMagic, 4) != 0)
      throw std::runtime_error("non-JSON frame for cpp worker");
    return parse_json(payload.substr(4));
  }

 private:
  void write_all(const char* p, size_t n) {
    while (n) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw std::runtime_error("send failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void read_all(char* p, size_t n) {
    while (n) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }
  int fd_;
};

int dial(const std::string& host, const std::string& port) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
    throw std::runtime_error("resolve failed: " + host);
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) throw std::runtime_error("connect failed: " + host + ":" + port);
  return fd;
}

// -------------------------------------------------------------- registry

using Fn = std::function<Json(const JsonArr&)>;

std::map<std::string, Fn>& registry() {
  static std::map<std::string, Fn> r;
  return r;
}

void register_function(const std::string& name, Fn fn) {
  registry()[name] = std::move(fn);
}

void install_functions() {
  register_function("add", [](const JsonArr& a) {
    return Json::of(a.at(0).as_number() + a.at(1).as_number());
  });
  register_function("mul", [](const JsonArr& a) {
    return Json::of(a.at(0).as_number() * a.at(1).as_number());
  });
  register_function("concat", [](const JsonArr& a) {
    std::string out;
    for (const auto& v : a) out += v.s;
    return Json::of(out);
  });
  register_function("vec_sum", [](const JsonArr& a) {
    double total = 0;
    for (const auto& v : a.at(0).arr) total += v.as_number();
    return Json::of(total);
  });
  // something a native worker is FOR: a tight numeric loop
  register_function("monte_carlo_pi", [](const JsonArr& a) {
    auto n = static_cast<int64_t>(a.at(0).as_number());
    std::mt19937_64 rng(42);
    std::uniform_real_distribution<double> u(0.0, 1.0);
    int64_t in = 0;
    for (int64_t k = 0; k < n; ++k) {
      double x = u(rng), y = u(rng);
      if (x * x + y * y <= 1.0) ++in;
    }
    return Json::of(4.0 * static_cast<double>(in) / static_cast<double>(n));
  });
  register_function("fail_on_purpose", [](const JsonArr&) -> Json {
    throw std::runtime_error("intentional failure from C++");
  });
}

// ------------------------------------------------------------ worker loop

int worker_main(int argc, char** argv) {
  std::string address, node_id = "node-0", host_id = "host-0";
  for (int k = 1; k < argc; ++k) {
    std::string a = argv[k];
    if (a == "--address" && k + 1 < argc) address = argv[++k];
    else if (a == "--node" && k + 1 < argc) node_id = argv[++k];
    else if (a == "--host" && k + 1 < argc) host_id = argv[++k];
  }
  if (address.empty()) {
    std::fprintf(stderr, "usage: cpp_worker --address host:port\n");
    return 2;
  }
  auto colon = address.rfind(':');
  install_functions();

  Conn conn(dial(address.substr(0, colon), address.substr(colon + 1)));
  std::mt19937_64 rng(std::random_device{}());
  char widbuf[32];
  std::snprintf(widbuf, sizeof(widbuf), "cpp-%016llx",
                static_cast<unsigned long long>(rng()));
  std::string wid = widbuf;

  Json reg = Json::object();
  reg.set("type", Json::of("register"));
  reg.set("rid", Json::of(static_cast<int64_t>(1)));
  reg.set("wid", Json::of(wid));
  reg.set("kind", Json::of("worker"));
  reg.set("codec", Json::of("json"));
  reg.set("language", Json::of("cpp"));
  reg.set("pid", Json::of(static_cast<int64_t>(::getpid())));
  reg.set("node_id", Json::of(node_id));
  reg.set("host", Json::of(host_id));
  Json fns = Json::array();
  for (const auto& kv : registry()) fns.arr.push_back(Json::of(kv.first));
  reg.set("functions", fns);
  conn.send(reg);
  Json hello = conn.recv();
  const Json* ok = hello.get("ok");
  if (!ok || !ok->b) {
    std::fprintf(stderr, "registration refused\n");
    return 1;
  }
  std::fprintf(stderr, "cpp worker %s ready (%zu functions)\n", wid.c_str(),
               registry().size());

  while (true) {
    Json msg = conn.recv();
    const Json* type = msg.get("type");
    if (!type) continue;
    if (type->s == "exit" || type->s == "die") return 0;
    if (type->s != "exec") continue;
    const Json* spec = msg.get("spec");
    if (!spec) continue;
    const Json* tid = spec->get("task_id");
    const Json* fname = spec->get("func_name");
    const Json* args = spec->get("args");

    Json done = Json::object();
    done.set("type", Json::of("task_done"));
    done.set("wid", Json::of(wid));
    Json echo = Json::object();
    echo.set("task_id", tid ? *tid : Json::null());
    echo.set("kind", Json::of("task"));
    echo.set("num_returns", Json::of(static_cast<int64_t>(1)));
    done.set("spec", echo);

    Json value = Json::null();
    std::string error;
    try {
      if (!fname) throw std::runtime_error("spec missing func_name");
      auto it = registry().find(fname->s);
      if (it == registry().end())
        throw std::runtime_error("unknown cpp function: " + fname->s);
      value = it->second(args ? args->arr : JsonArr{});
    } catch (const std::exception& e) {
      error = e.what();
    }
    if (error.empty()) done.set("error", Json::null());
    else done.set("error", Json::of(error));
    Json results = Json::array();
    Json res = Json::array();
    res.arr.push_back(Json::of((tid ? tid->s : std::string()) + "r0000"));
    res.arr.push_back(Json::of("inline"));
    res.arr.push_back(value);
    res.arr.push_back(Json::of(static_cast<int64_t>(0)));
    results.arr.push_back(res);
    done.set("results", results);
    conn.send(done);
  }
}

}  // namespace ray_tpu

int main(int argc, char** argv) {
  try {
    return ray_tpu::worker_main(argc, argv);
  } catch (const std::exception& e) {
    std::fprintf(stderr, "cpp worker fatal: %s\n", e.what());
    return 1;
  }
}
