// Native object-plane server: serves sealed shm objects to other hosts.
//
// Reference capability: the C++ object manager's chunked push/pull plane
// (reference: src/ray/object_manager/object_manager.h:128 — node-to-node
// transfer with admission control). TPU build: objects are sealed tmpfs
// files (file-per-object store) or spill-tier files, so the server is pure
// IO — epoll-free blocking threads, zero Python on the hot path, streaming
// straight from the page cache with a trivial binary wire format:
//
//   request:  [u32 oid_len LE][oid bytes]
//   response: [u64 size LE][payload bytes]   (size = UINT64_MAX → not found)
//
// Exposed via a C API loaded with ctypes (ray_tpu/_private/native_object_server.py):
//   objsrv_start(prefix, spill_dir, bind_host, port) -> handle
//   objsrv_port(handle) -> bound port
//   objsrv_stop(handle)
//
// Build: g++ -O2 -shared -fPIC -o build/libobjserver.so object_server.cc -lpthread

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/sendfile.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <set>
#include <string>
#include <vector>

namespace {

constexpr uint64_t kNotFound = ~0ULL;
constexpr uint32_t kMaxOidLen = 4096;

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::string prefix;     // e.g. /dev/shm/rtpu_<session>_
  std::string spill_dir;  // e.g. /tmp/ray_tpu/spill_<session>
  std::atomic<bool> stop{false};
  pthread_t accept_thread{};
  // live connection fds + count: stop() shuts them down and waits for the
  // detached conn threads to exit before the Server is freed (no UAF)
  std::mutex conn_mu;
  std::set<int> conn_fds;
  std::atomic<int> conn_count{0};
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// oid must be a plain hex-ish token: reject path traversal outright
bool oid_ok(const std::string& oid) {
  if (oid.empty() || oid.size() > kMaxOidLen) return false;
  for (char c : oid) {
    if (!(isalnum(static_cast<unsigned char>(c)) || c == '_' || c == '-')) {
      return false;
    }
  }
  return true;
}

int open_object(const Server* s, const std::string& oid, uint64_t* size) {
  for (const std::string& path :
       {s->prefix + oid, s->spill_dir + "/" + oid}) {
    int fd = open(path.c_str(), O_RDONLY);
    if (fd >= 0) {
      struct stat st;
      if (fstat(fd, &st) == 0) {
        *size = static_cast<uint64_t>(st.st_size);
        return fd;
      }
      close(fd);
    }
  }
  return -1;
}

struct ConnArg {
  Server* srv;
  int fd;
};

void* conn_main(void* argp) {
  ConnArg* arg = static_cast<ConnArg*>(argp);
  Server* s = arg->srv;
  int fd = arg->fd;
  delete arg;
  int one = 1;
  {
    std::lock_guard<std::mutex> g(s->conn_mu);
    s->conn_fds.insert(fd);
  }
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (!s->stop.load()) {
    uint32_t oid_len = 0;
    if (!read_exact(fd, &oid_len, sizeof(oid_len))) break;
    if (oid_len == 0 || oid_len > kMaxOidLen) break;
    std::string oid(oid_len, '\0');
    if (!read_exact(fd, &oid[0], oid_len)) break;
    uint64_t size = kNotFound;
    int obj_fd = -1;
    if (oid_ok(oid)) obj_fd = open_object(s, oid, &size);
    if (obj_fd < 0) {
      uint64_t nf = kNotFound;
      if (!write_exact(fd, &nf, sizeof(nf))) break;
      continue;
    }
    bool ok = write_exact(fd, &size, sizeof(size));
    off_t off = 0;
    while (ok && static_cast<uint64_t>(off) < size) {
      ssize_t sent = sendfile(fd, obj_fd, &off, size - off);
      if (sent <= 0) {
        // sendfile can fail across fs types; fall back to read/write
        char buf[1 << 16];
        ssize_t r = pread(obj_fd, buf, sizeof(buf), off);
        if (r <= 0 || !write_exact(fd, buf, static_cast<size_t>(r))) {
          ok = false;
          break;
        }
        off += r;
      }
    }
    close(obj_fd);
    if (!ok) break;
  }
  {
    std::lock_guard<std::mutex> g(s->conn_mu);
    s->conn_fds.erase(fd);
  }
  close(fd);
  s->conn_count.fetch_sub(1);
  return nullptr;
}

void* accept_main(void* argp) {
  Server* s = static_cast<Server*>(argp);
  while (!s->stop.load()) {
    int fd = accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) break;
      continue;
    }
    if (s->stop.load()) {
      close(fd);
      break;
    }
    auto* arg = new ConnArg{s, fd};
    s->conn_count.fetch_add(1);
    pthread_t t;
    if (pthread_create(&t, nullptr, conn_main, arg) == 0) {
      pthread_detach(t);
    } else {
      s->conn_count.fetch_sub(1);
      close(fd);
      delete arg;
    }
  }
  close(s->listen_fd);
  return nullptr;
}

}  // namespace

extern "C" {

void* objsrv_start(const char* prefix, const char* spill_dir,
                   const char* bind_host, int port) {
  auto* s = new Server;
  s->prefix = prefix;
  s->spill_dir = spill_dir;
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(s->listen_fd, 256) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  s->port = ntohs(addr.sin_port);
  if (pthread_create(&s->accept_thread, nullptr, accept_main, s) != 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  return s;
}

int objsrv_port(void* handle) {
  return handle ? static_cast<Server*>(handle)->port : -1;
}

void objsrv_stop(void* handle) {
  if (!handle) return;
  auto* s = static_cast<Server*>(handle);
  s->stop.store(true);
  // unblock accept(): shutdown works regardless of the bind address; the
  // loopback self-connect is belt-and-braces for platforms where shutdown
  // on a listening socket is a no-op
  shutdown(s->listen_fd, SHUT_RDWR);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd >= 0) {
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(s->port));
    inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
    connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    close(fd);
  }
  pthread_join(s->accept_thread, nullptr);
  // kick live connections off their blocking reads/writes, then wait for
  // every conn thread to finish before freeing the Server (UAF guard)
  {
    std::lock_guard<std::mutex> g(s->conn_mu);
    for (int cfd : s->conn_fds) shutdown(cfd, SHUT_RDWR);
  }
  for (int spins = 0; s->conn_count.load() > 0 && spins < 2000; ++spins) {
    usleep(5000);  // up to ~10s; threads exit as soon as their IO aborts
  }
  if (s->conn_count.load() == 0) {
    delete s;
  }  // else: leak the tiny Server rather than free it under a live thread
}

}  // extern "C"
