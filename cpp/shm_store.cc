// Shared-memory arena object store — the native core of the per-node object
// plane. One mmap'd tmpfs file holds a header (robust process-shared mutex +
// object index + free list) and a data region; every process of a session
// maps the same file, so sealed objects are zero-copy readable everywhere.
//
// (reference capability: src/ray/object_manager/plasma/ — PlasmaStore over
// dlmalloc'd shm with LRU eviction (eviction_policy.h:159) and fd passing
// (fling.cc). Design here is arena+offsets instead of fd-per-object: tmpfs
// is the transport, offsets are the handles, a robust pthread mutex replaces
// the store-server event loop for intra-node coordination.)
//
// Build: g++ -O2 -shared -fPIC -o libshmstore.so shm_store.cc -lpthread
//
// All functions return >=0 on success; negative codes:
//   -1 not found / no space (create: even after eviction)
//   -2 already exists / state error
//   -3 internal capacity (index or free-list full)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fcntl.h>
#include <pthread.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055414E4131ULL;  // "RTPUANA1"
constexpr uint32_t kOidLen = 40;
constexpr uint32_t kMaxSlots = 32768;
constexpr uint32_t kMaxHoles = 8192;

enum State : uint32_t { kFree = 0, kCreating = 1, kSealed = 2, kDeleting = 3 };

struct Entry {
  char oid[kOidLen];
  uint64_t offset;
  uint64_t size;
  uint32_t state;
  uint32_t refcount;
  uint64_t lru_tick;
};

struct Hole {
  uint64_t offset;
  uint64_t size;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // bytes in the data region
  uint64_t data_start;    // file offset where data begins
  uint64_t bump;          // next never-used byte (relative to data_start)
  uint64_t tick;          // LRU clock
  uint64_t used;          // live bytes (creating+sealed)
  uint32_t n_slots;
  uint32_t n_holes;
  pthread_mutex_t mutex;
  Entry slots[kMaxSlots];
  Hole holes[kMaxHoles];
};

struct Store {
  Header* hdr;
  uint8_t* base;          // mapping base
  uint64_t map_len;
  int fd;
};

void lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mutex);  // holder died
}

void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

Entry* find(Header* h, const char* oid) {
  for (uint32_t i = 0; i < h->n_slots; i++) {
    Entry& e = h->slots[i];
    if (e.state != kFree && strncmp(e.oid, oid, kOidLen) == 0) return &e;
  }
  return nullptr;
}

Entry* free_slot(Header* h) {
  for (uint32_t i = 0; i < h->n_slots; i++)
    if (h->slots[i].state == kFree) return &h->slots[i];
  if (h->n_slots < kMaxSlots) return &h->slots[h->n_slots++];
  return nullptr;
}

// return a hole to the free list, merging with adjacent holes
void add_hole(Header* h, uint64_t offset, uint64_t size) {
  if (size == 0) return;
  if (offset + size == h->bump) {  // tail hole: give back to the bump region
    h->bump = offset;
    // absorb any hole now adjacent to the (moved) bump pointer
    bool merged = true;
    while (merged) {
      merged = false;
      for (uint32_t i = 0; i < h->n_holes; i++) {
        if (h->holes[i].offset + h->holes[i].size == h->bump) {
          h->bump = h->holes[i].offset;
          h->holes[i] = h->holes[--h->n_holes];
          merged = true;
          break;
        }
      }
    }
    return;
  }
  for (uint32_t i = 0; i < h->n_holes; i++) {
    Hole& o = h->holes[i];
    if (o.offset + o.size == offset) {        // extend o rightward
      o.size += size;
      return;
    }
    if (offset + size == o.offset) {          // extend o leftward
      o.offset = offset;
      o.size += size;
      return;
    }
  }
  if (h->n_holes < kMaxHoles) h->holes[h->n_holes++] = {offset, size};
  // else: the space is leaked until session cleanup — counted, not fatal
}

// best-fit from the free list, else bump; -1 if no contiguous run fits
int64_t carve(Header* h, uint64_t size) {
  uint32_t best = kMaxHoles;
  uint64_t best_sz = UINT64_MAX;
  for (uint32_t i = 0; i < h->n_holes; i++) {
    if (h->holes[i].size >= size && h->holes[i].size < best_sz) {
      best = i;
      best_sz = h->holes[i].size;
    }
  }
  if (best != kMaxHoles) {
    Hole& o = h->holes[best];
    uint64_t off = o.offset;
    o.offset += size;
    o.size -= size;
    if (o.size == 0) h->holes[best] = h->holes[--h->n_holes];
    return (int64_t)off;
  }
  if (h->bump + size <= h->capacity) {
    uint64_t off = h->bump;
    h->bump += size;
    return (int64_t)off;
  }
  return -1;
}

// evict ONE least-recently-used sealed+unpinned object; false if none
bool evict_lru(Header* h) {
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < h->n_slots; i++) {
    Entry& e = h->slots[i];
    if (e.state == kSealed && e.refcount == 0 &&
        (!victim || e.lru_tick < victim->lru_tick))
      victim = &e;
  }
  if (!victim) return false;
  add_hole(h, victim->offset, victim->size);
  h->used -= victim->size;
  victim->state = kFree;
  return true;
}

}  // namespace

extern "C" {

// Open (create=1: initialize if new) the arena at `path` with `capacity`
// data bytes. Returns an opaque handle or null.
void* rtpu_store_open(const char* path, uint64_t capacity, int create) {
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + capacity;
  flock(fd, LOCK_EX);  // serialize first-time initialization
  struct stat st;
  fstat(fd, &st);
  bool fresh = st.st_size == 0;
  if (fresh) {
    if (!create || ftruncate(fd, (off_t)total) != 0) {
      flock(fd, LOCK_UN);
      close(fd);
      return nullptr;
    }
  } else {
    total = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    flock(fd, LOCK_UN);
    close(fd);
    return nullptr;
  }
  Header* hdr = (Header*)mem;
  if (fresh) {
    memset(hdr, 0, sizeof(Header));
    hdr->magic = kMagic;
    hdr->capacity = total - sizeof(Header);
    hdr->data_start = sizeof(Header);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
  } else if (hdr->magic != kMagic) {
    munmap(mem, total);
    flock(fd, LOCK_UN);
    close(fd);
    return nullptr;
  }
  flock(fd, LOCK_UN);
  Store* s = new Store{hdr, (uint8_t*)mem, total, fd};
  return s;
}

void rtpu_store_close(void* handle) {
  Store* s = (Store*)handle;
  munmap(s->base, s->map_len);
  close(s->fd);
  delete s;
}

// Allocate `size` bytes for `oid`. Evicts LRU sealed objects as needed.
// Returns file offset of the data, or a negative code.
int64_t rtpu_store_create(void* handle, const char* oid, uint64_t size) {
  Store* s = (Store*)handle;
  Header* h = s->hdr;
  lock(h);
  Entry* prev = find(h, oid);
  if (prev) {
    if (prev->state == kCreating) {
      // orphaned create: ids are single-writer, so a kCreating entry for a
      // new create means the previous writer died mid-put (the robust mutex
      // already recovered the lock). Reclaim and start over.
      add_hole(h, prev->offset, prev->size);
      h->used -= prev->size;
      prev->state = kFree;
    } else {
      unlock(h);
      return -2;
    }
  }
  if (size > h->capacity) {
    unlock(h);
    return -1;
  }
  int64_t off;
  while ((off = carve(h, size)) < 0) {
    if (!evict_lru(h)) {
      unlock(h);
      return -1;
    }
  }
  Entry* e = free_slot(h);
  if (!e) {
    add_hole(h, (uint64_t)off, size);
    unlock(h);
    return -3;
  }
  strncpy(e->oid, oid, kOidLen);
  e->offset = (uint64_t)off;
  e->size = size;
  e->state = kCreating;
  e->refcount = 0;
  e->lru_tick = ++h->tick;
  h->used += size;
  int64_t abs_off = (int64_t)(h->data_start + (uint64_t)off);
  unlock(h);
  return abs_off;
}

int rtpu_store_seal(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state != kCreating) {
    unlock(h);
    return e ? -2 : -1;
  }
  e->state = kSealed;
  e->lru_tick = ++h->tick;
  unlock(h);
  return 0;
}

// Pin + locate a sealed object. Returns absolute offset, fills *size_out.
int64_t rtpu_store_get(void* handle, const char* oid, uint64_t* size_out) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state != kSealed) {
    unlock(h);
    return -1;
  }
  e->refcount++;
  e->lru_tick = ++h->tick;
  *size_out = e->size;
  int64_t off = (int64_t)(h->data_start + e->offset);
  unlock(h);
  return off;
}

int rtpu_store_release(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (e && e->refcount > 0) {
    e->refcount--;
    if (e->refcount == 0 && e->state == kDeleting) {
      // deferred delete: last reader unpinned
      add_hole(h, e->offset, e->size);
      h->used -= e->size;
      e->state = kFree;
    }
  }
  unlock(h);
  return e ? 0 : -1;
}

int rtpu_store_contains(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  int ok = (e && e->state == kSealed) ? 1 : 0;
  unlock(h);
  return ok;
}

int64_t rtpu_store_size(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  int64_t out = (e && e->state == kSealed) ? (int64_t)e->size : -1;
  unlock(h);
  return out;
}

int rtpu_store_delete(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state == kDeleting) {
    unlock(h);
    return -1;
  }
  if (e->refcount > 0) {
    e->state = kDeleting;  // space reclaimed when the last reader releases
  } else {
    add_hole(h, e->offset, e->size);
    h->used -= e->size;
    e->state = kFree;
  }
  unlock(h);
  return 0;
}

uint64_t rtpu_store_used(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  uint64_t u = h->used;
  unlock(h);
  return u;
}

uint64_t rtpu_store_capacity(void* handle) {
  return ((Store*)handle)->hdr->capacity;
}

uint32_t rtpu_store_num_objects(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  uint32_t n = 0;
  for (uint32_t i = 0; i < h->n_slots; i++)
    if (h->slots[i].state == kSealed) n++;
  unlock(h);
  return n;
}

}  // extern "C"
