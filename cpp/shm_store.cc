// Shared-memory arena object store — the native core of the per-node object
// plane. One mmap'd tmpfs file holds a header (robust process-shared mutex +
// hash-indexed object table + free list + per-pid pin registry) and a data
// region; every process of a session maps the same file, so sealed objects
// are zero-copy readable everywhere.
//
// (reference capability: src/ray/object_manager/plasma/ — PlasmaStore over
// dlmalloc'd shm with LRU eviction (eviction_policy.h:159) and fd passing
// (fling.cc). Design here is arena+offsets instead of fd-per-object: tmpfs
// is the transport, offsets are the handles, a robust pthread mutex replaces
// the store-server event loop for intra-node coordination. The pin registry
// plays the role of plasma's per-client object table: a client that dies
// holding pins has them released, so eviction can't wedge.)
//
// Build: g++ -O2 -shared -fPIC -o libshmstore.so shm_store.cc -lpthread
//
// All functions return >=0 on success; negative codes:
//   -1 not found / no space (create: even after eviction)
//   -2 already exists / state error
//   -3 internal capacity (index or free-list full)
//   -4 object larger than the whole data region (create_noevict only)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/file.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055414E4136ULL;  // "RTPUANA6"
constexpr uint32_t kOidLen = 40;
constexpr uint32_t kMaxSlots = 32768;
constexpr uint32_t kMaxHoles = 8192;
constexpr uint32_t kHashSize = 65536;  // power of two, ~2x kMaxSlots
// One record per live (pid, slot) pin edge. Sized at kMaxSlots: overflowing
// it means >32k simultaneously pinned objects on one host — a pin taken
// past the cap still counts in refcount but is unattributable, so a reaper
// can't recover it if its holder is SIGKILLed (see pin_record).
constexpr uint32_t kMaxPins = 32768;

enum State : uint32_t { kFree = 0, kCreating = 1, kSealed = 2, kDeleting = 3 };

struct Entry {
  char oid[kOidLen];
  uint64_t offset;
  uint64_t size;
  uint32_t state;
  uint32_t refcount;
  uint64_t lru_tick;
  uint32_t hnext;        // hash-chain link: next slot index + 1, 0 = end
  int32_t creator_pid;   // writer of a kCreating entry (dead-writer reclaim)
};

struct Hole {
  uint64_t offset;
  uint64_t size;
};

// One (pid, slot) pin edge. Every record in [0, n_pins) is live (the
// registry swap-compacts on free), and a live record implies `count` refs
// on that slot's entry, so the slot cannot be recycled under it — reaping
// a dead pid's records is therefore always attributable.
struct PinRec {
  int32_t pid;
  uint32_t slot;
  uint32_t count;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;      // bytes in the data region
  uint64_t data_start;    // file offset where data begins
  uint64_t bump;          // next never-used byte (relative to data_start)
  uint64_t tick;          // LRU clock
  uint64_t used;          // live bytes (creating+sealed)
  uint32_t n_slots;
  uint32_t n_holes;
  uint32_t n_pins;        // high-water mark of the pin registry
  uint32_t slot_free_head;  // freed-slot stack: slot index + 1, 0 = empty
  pthread_mutex_t mutex;
  uint32_t hash[kHashSize];  // bucket heads: slot index + 1, 0 = empty
  Entry slots[kMaxSlots];
  Hole holes[kMaxHoles];
  PinRec pins[kMaxPins];
};

struct Store {
  Header* hdr;
  uint8_t* base;          // mapping base
  uint64_t map_len;
  int fd;
};

void lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mutex);  // holder died
}

void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

// ------------------------------------------------------------ hash index
// FNV-1a over the fixed-width (null-padded) id, masked to a bucket. The
// linear kMaxSlots scan this replaces was the dominant per-op cost once a
// few thousand objects were resident.

uint32_t oid_bucket(const char* oid) {
  char buf[kOidLen];
  memset(buf, 0, sizeof buf);
  strncpy(buf, oid, kOidLen);
  uint32_t hsh = 2166136261u;
  for (uint32_t i = 0; i < kOidLen; i++) {
    hsh ^= (uint8_t)buf[i];
    hsh *= 16777619u;
  }
  return hsh & (kHashSize - 1);
}

void hash_insert(Header* h, uint32_t idx) {
  uint32_t b = oid_bucket(h->slots[idx].oid);
  h->slots[idx].hnext = h->hash[b];
  h->hash[b] = idx + 1;
}

void hash_remove(Header* h, uint32_t idx) {
  uint32_t b = oid_bucket(h->slots[idx].oid);
  uint32_t* link = &h->hash[b];
  while (*link) {
    uint32_t cur = *link - 1;
    if (cur == idx) {
      *link = h->slots[cur].hnext;
      h->slots[cur].hnext = 0;
      return;
    }
    link = &h->slots[cur].hnext;
  }
}

Entry* find(Header* h, const char* oid) {
  uint32_t link = h->hash[oid_bucket(oid)];
  while (link) {
    Entry& e = h->slots[link - 1];
    if (e.state != kFree && strncmp(e.oid, oid, kOidLen) == 0) return &e;
    link = e.hnext;
  }
  return nullptr;
}

// pop a recycled slot (freed slots are stacked through hnext — the linear
// any-kFree scan this replaces made every create O(live objects) once the
// table had churned), else extend the high-water region
Entry* free_slot(Header* h) {
  if (h->slot_free_head) {
    uint32_t idx = h->slot_free_head - 1;
    h->slot_free_head = h->slots[idx].hnext;
    h->slots[idx].hnext = 0;
    return &h->slots[idx];
  }
  if (h->n_slots < kMaxSlots) return &h->slots[h->n_slots++];
  return nullptr;
}

// ------------------------------------------------------------- free list

// return a hole to the free list, merging with adjacent holes
void add_hole(Header* h, uint64_t offset, uint64_t size) {
  if (size == 0) return;
  if (offset + size == h->bump) {  // tail hole: give back to the bump region
    h->bump = offset;
    // absorb any hole now adjacent to the (moved) bump pointer
    bool merged = true;
    while (merged) {
      merged = false;
      for (uint32_t i = 0; i < h->n_holes; i++) {
        if (h->holes[i].offset + h->holes[i].size == h->bump) {
          h->bump = h->holes[i].offset;
          h->holes[i] = h->holes[--h->n_holes];
          merged = true;
          break;
        }
      }
    }
    return;
  }
  for (uint32_t i = 0; i < h->n_holes; i++) {
    Hole& o = h->holes[i];
    if (o.offset + o.size == offset) {        // extend o rightward
      o.size += size;
      return;
    }
    if (offset + size == o.offset) {          // extend o leftward
      o.offset = offset;
      o.size += size;
      return;
    }
  }
  if (h->n_holes < kMaxHoles) h->holes[h->n_holes++] = {offset, size};
  // else: the space is leaked until session cleanup — counted, not fatal
}

// retire an entry: unlink from the hash index, return its run to the free
// list, drop it from the live byte count (caller owns the lock)
void free_entry(Header* h, Entry* e) {
  uint32_t idx = (uint32_t)(e - h->slots);
  hash_remove(h, idx);
  add_hole(h, e->offset, e->size);
  h->used -= e->size;
  e->state = kFree;
  e->hnext = h->slot_free_head;  // push onto the freed-slot stack
  h->slot_free_head = idx + 1;
}

// ---------------------------------------------------------- pin registry

// free record i by moving the last live record into its place (if i IS the
// last, this self-assigns then shrinks) — scans stay O(live pin edges)
void pin_drop_at(Header* h, uint32_t i) {
  h->pins[i] = h->pins[h->n_pins - 1];
  h->n_pins--;
}

void pin_record(Header* h, uint32_t slot) {
  int32_t pid = (int32_t)getpid();
  for (uint32_t i = 0; i < h->n_pins; i++) {
    PinRec& r = h->pins[i];
    if (r.pid == pid && r.slot == slot) {
      r.count++;
      return;
    }
  }
  if (h->n_pins < kMaxPins) h->pins[h->n_pins++] = {pid, slot, 1};
  // registry full (>32k live pin edges): the pin still counts in refcount
  // but is unattributable — if its holder dies without releasing, that ref
  // leaks until session teardown. Reads/writes keep working; puts degrade
  // to the spill tier once unevictable bytes fill the arena.
}

void pin_unrecord(Header* h, uint32_t slot) {
  int32_t pid = (int32_t)getpid();
  for (uint32_t i = 0; i < h->n_pins; i++) {
    PinRec& r = h->pins[i];
    if (r.pid == pid && r.slot == slot) {
      if (--r.count == 0) pin_drop_at(h, i);
      return;
    }
  }
}

// drop `count` refs a (dead or exiting) pid held on a slot; reclaims a
// deferred delete whose last reader this was
void drop_refs(Header* h, uint32_t slot, uint32_t count) {
  Entry& e = h->slots[slot];
  if (e.state == kFree) return;  // invariant says never, but stay safe
  e.refcount = count >= e.refcount ? 0 : e.refcount - count;
  if (e.refcount == 0 && e.state == kDeleting) free_entry(h, &e);
}

// release every pin held by `pid`; with pid<0, every pin whose holder no
// longer exists. Returns the number of pin edges released.
// Known limitation: pid reuse between a holder's death and the reap makes
// kill(pid,0) succeed for the recycled pid, so that edge is skipped and its
// bytes stay unevictable until session teardown (puts degrade to the spill
// tier, no corruption). A (pid, start-time) identity — as the autoscaler's
// pid registry uses — would close this.
int release_pins_of(Header* h, int32_t pid) {
  int released = 0;
  uint32_t i = 0;
  while (i < h->n_pins) {
    PinRec& r = h->pins[i];
    bool match = pid >= 0 ? r.pid == pid
                          : (kill(r.pid, 0) != 0 && errno == ESRCH);
    if (!match) {
      i++;
      continue;
    }
    drop_refs(h, r.slot, r.count);
    released += (int)r.count;
    pin_drop_at(h, i);  // re-examine the record swapped into slot i
  }
  return released;
}

// ------------------------------------------------------------- allocator

// best-fit from the free list, else bump; -1 if no contiguous run fits
int64_t carve(Header* h, uint64_t size) {
  uint32_t best = kMaxHoles;
  uint64_t best_sz = UINT64_MAX;
  for (uint32_t i = 0; i < h->n_holes; i++) {
    if (h->holes[i].size >= size && h->holes[i].size < best_sz) {
      best = i;
      best_sz = h->holes[i].size;
    }
  }
  if (best != kMaxHoles) {
    Hole& o = h->holes[best];
    uint64_t off = o.offset;
    o.offset += size;
    o.size -= size;
    if (o.size == 0) h->holes[best] = h->holes[--h->n_holes];
    return (int64_t)off;
  }
  if (h->bump + size <= h->capacity) {
    uint64_t off = h->bump;
    h->bump += size;
    return (int64_t)off;
  }
  return -1;
}

// evict ONE least-recently-used sealed+unpinned object; false if none
bool evict_lru(Header* h) {
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < h->n_slots; i++) {
    Entry& e = h->slots[i];
    if (e.state == kSealed && e.refcount == 0 &&
        (!victim || e.lru_tick < victim->lru_tick))
      victim = &e;
  }
  if (!victim) return false;
  free_entry(h, victim);
  return true;
}

// shared create body; `evict` selects plasma-style LRU eviction vs the
// caller-orchestrated path (Python spills the victim first, then retries)
int64_t create_impl(Store* s, const char* oid, uint64_t size, bool evict) {
  Header* h = s->hdr;
  lock(h);
  Entry* prev = find(h, oid);
  if (prev) {
    bool creator_dead =
        prev->creator_pid > 0 &&
        kill(prev->creator_pid, 0) != 0 && errno == ESRCH;
    if (prev->state == kCreating && creator_dead) {
      // orphaned create: the writer died mid-put (the robust mutex already
      // recovered the lock). Reclaim and start over.
      free_entry(h, prev);
    } else {
      // sealed/deleting — or a kCreating entry whose writer is STILL ALIVE
      // (two processes re-putting the same fetched object): freeing a live
      // writer's run out from under its pwrite would publish torn bytes.
      // -2 lets the caller treat it as already-present (the Python side
      // preserves its copy in the spill tier if the id isn't readable yet).
      unlock(h);
      return -2;
    }
  }
  if (size > h->capacity) {
    unlock(h);
    return evict ? -1 : -4;
  }
  int64_t off;
  bool tried_reap = false;
  while ((off = carve(h, size)) < 0) {
    if (!evict) {
      unlock(h);
      return -1;
    }
    if (evict_lru(h)) continue;
    // nothing evictable: pins held by dead processes may be the blocker
    if (!tried_reap) {
      tried_reap = true;
      if (release_pins_of(h, -1) > 0) continue;
    }
    unlock(h);
    return -1;
  }
  Entry* e = free_slot(h);
  if (!e) {
    add_hole(h, (uint64_t)off, size);
    unlock(h);
    return -3;
  }
  memset(e->oid, 0, kOidLen);
  strncpy(e->oid, oid, kOidLen);
  e->offset = (uint64_t)off;
  e->size = size;
  e->state = kCreating;
  e->refcount = 0;
  e->creator_pid = (int32_t)getpid();
  e->lru_tick = ++h->tick;
  hash_insert(h, (uint32_t)(e - h->slots));
  h->used += size;
  int64_t abs_off = (int64_t)(h->data_start + (uint64_t)off);
  unlock(h);
  return abs_off;
}

}  // namespace

extern "C" {

// Open (create=1: initialize if new) the arena at `path` with `capacity`
// data bytes. Returns an opaque handle or null.
void* rtpu_store_open(const char* path, uint64_t capacity, int create) {
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(Header) + capacity;
  flock(fd, LOCK_EX);  // serialize first-time initialization
  struct stat st;
  fstat(fd, &st);
  bool fresh = st.st_size == 0;
  if (fresh) {
    if (!create || ftruncate(fd, (off_t)total) != 0) {
      flock(fd, LOCK_UN);
      close(fd);
      return nullptr;
    }
  } else {
    total = (uint64_t)st.st_size;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    flock(fd, LOCK_UN);
    close(fd);
    return nullptr;
  }
  Header* hdr = (Header*)mem;
  if (fresh) {
    // no memset: the ftruncate'd tmpfs pages already read back zero, and
    // zeroing ~3 MB of header would fault every page at session start
    hdr->magic = kMagic;
    hdr->capacity = total - sizeof(Header);
    hdr->data_start = sizeof(Header);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
  } else if (hdr->magic != kMagic) {
    munmap(mem, total);
    flock(fd, LOCK_UN);
    close(fd);
    return nullptr;
  }
  flock(fd, LOCK_UN);
  Store* s = new Store{hdr, (uint8_t*)mem, total, fd};
  return s;
}

void rtpu_store_close(void* handle) {
  Store* s = (Store*)handle;
  munmap(s->base, s->map_len);
  close(s->fd);
  delete s;
}

// Allocate `size` bytes for `oid`. Evicts LRU sealed objects as needed.
// Returns file offset of the data, or a negative code.
int64_t rtpu_store_create(void* handle, const char* oid, uint64_t size) {
  return create_impl((Store*)handle, oid, size, true);
}

// Allocate without evicting: -1 means "no contiguous run; spill/evict
// something and retry", -4 means "larger than the whole data region". The
// Python store drives this variant so eviction can SPILL victims to the
// disk tier instead of dropping the only copy.
int64_t rtpu_store_create_noevict(void* handle, const char* oid,
                                  uint64_t size) {
  return create_impl((Store*)handle, oid, size, false);
}

int rtpu_store_seal(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state != kCreating) {
    unlock(h);
    return e ? -2 : -1;
  }
  e->state = kSealed;
  e->lru_tick = ++h->tick;
  unlock(h);
  return 0;
}

// Pin + locate a sealed object. Returns absolute offset, fills *size_out.
int64_t rtpu_store_get(void* handle, const char* oid, uint64_t* size_out) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state != kSealed) {
    unlock(h);
    return -1;
  }
  e->refcount++;
  pin_record(h, (uint32_t)(e - h->slots));
  e->lru_tick = ++h->tick;
  *size_out = e->size;
  int64_t off = (int64_t)(h->data_start + e->offset);
  unlock(h);
  return off;
}

int rtpu_store_release(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (e && e->refcount > 0) {
    pin_unrecord(h, (uint32_t)(e - h->slots));
    e->refcount--;
    if (e->refcount == 0 && e->state == kDeleting) {
      // deferred delete: last reader unpinned
      free_entry(h, e);
    }
  }
  unlock(h);
  return e ? 0 : -1;
}

int rtpu_store_contains(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  int ok = (e && e->state == kSealed) ? 1 : 0;
  unlock(h);
  return ok;
}

int64_t rtpu_store_size(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  int64_t out = (e && e->state == kSealed) ? (int64_t)e->size : -1;
  unlock(h);
  return out;
}

int rtpu_store_delete(void* handle, const char* oid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* e = find(h, oid);
  if (!e || e->state == kDeleting) {
    unlock(h);
    return -1;
  }
  if (e->refcount > 0) {
    e->state = kDeleting;  // space reclaimed when the last reader releases
  } else {
    free_entry(h, e);
  }
  unlock(h);
  return 0;
}

// Copy the id of the current LRU sealed+unpinned object into `oid_out`
// (caller buffer >= 41 bytes; null-terminated here). Returns 0, or -1 when
// nothing is evictable.
int rtpu_store_lru_victim(void* handle, char* oid_out) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  Entry* victim = nullptr;
  for (uint32_t i = 0; i < h->n_slots; i++) {
    Entry& e = h->slots[i];
    if (e.state == kSealed && e.refcount == 0 &&
        (!victim || e.lru_tick < victim->lru_tick))
      victim = &e;
  }
  if (!victim) {
    unlock(h);
    return -1;
  }
  memcpy(oid_out, victim->oid, kOidLen);
  oid_out[kOidLen] = '\0';
  unlock(h);
  return 0;
}

// Release every pin held by processes that no longer exist (worker SIGKILL
// with mapped views). Returns the number of pin edges released.
int rtpu_store_reap_dead(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  int n = release_pins_of(h, -1);
  unlock(h);
  return n;
}

// Release every pin held by `pid` (clean-exit path: a worker drops all its
// outstanding views in one call before disconnecting).
int rtpu_store_release_pid(void* handle, int32_t pid) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  int n = release_pins_of(h, pid);
  unlock(h);
  return n;
}

uint64_t rtpu_store_used(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  uint64_t u = h->used;
  unlock(h);
  return u;
}

uint64_t rtpu_store_capacity(void* handle) {
  return ((Store*)handle)->hdr->capacity;
}

uint32_t rtpu_store_num_objects(void* handle) {
  Header* h = ((Store*)handle)->hdr;
  lock(h);
  uint32_t n = 0;
  for (uint32_t i = 0; i < h->n_slots; i++)
    if (h->slots[i].state == kSealed) n++;
  unlock(h);
  return n;
}

}  // extern "C"
