// C-level test for the shm arena store: create/seal/get/release/delete,
// eviction under pressure, pin semantics, hole coalescing, multi-process
// sharing through fork, no-evict create + LRU victim query, and dead-pid
// pin reaping. Exits 0 on success; any failed check aborts.
//
// Build+run (also driven by tests/test_shm_arena.py):
//   g++ -O2 -o shm_store_test shm_store_test.cc -ldl -lpthread && ./shm_store_test

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dlfcn.h>
#include <sys/wait.h>
#include <unistd.h>

typedef void* (*open_fn)(const char*, uint64_t, int);
typedef void (*close_fn)(void*);
typedef int64_t (*create_fn)(void*, const char*, uint64_t);
typedef int (*seal_fn)(void*, const char*);
typedef int64_t (*get_fn)(void*, const char*, uint64_t*);
typedef int (*rel_fn)(void*, const char*);
typedef int (*contains_fn)(void*, const char*);
typedef int (*del_fn)(void*, const char*);
typedef uint64_t (*used_fn)(void*);
typedef int64_t (*create2_fn)(void*, const char*, uint64_t);
typedef int (*victim_fn)(void*, char*);
typedef int (*reap_fn)(void*);
typedef int (*relpid_fn)(void*, int32_t);

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                         \
    }                                                                   \
  } while (0)

int main(int argc, char** argv) {
  const char* libpath = argc > 1 ? argv[1] : "./libshmstore.so";
  const char* arena = argc > 2 ? argv[2] : "/dev/shm/rtpu_test_arena";
  unlink(arena);

  void* dl = dlopen(libpath, RTLD_NOW);
  CHECK(dl != nullptr);
  auto store_open = (open_fn)dlsym(dl, "rtpu_store_open");
  auto store_close = (close_fn)dlsym(dl, "rtpu_store_close");
  auto store_create = (create_fn)dlsym(dl, "rtpu_store_create");
  auto store_seal = (seal_fn)dlsym(dl, "rtpu_store_seal");
  auto store_get = (get_fn)dlsym(dl, "rtpu_store_get");
  auto store_release = (rel_fn)dlsym(dl, "rtpu_store_release");
  auto store_contains = (contains_fn)dlsym(dl, "rtpu_store_contains");
  auto store_delete = (del_fn)dlsym(dl, "rtpu_store_delete");
  auto store_used = (used_fn)dlsym(dl, "rtpu_store_used");
  auto store_create_noevict =
      (create2_fn)dlsym(dl, "rtpu_store_create_noevict");
  auto store_lru_victim = (victim_fn)dlsym(dl, "rtpu_store_lru_victim");
  auto store_reap_dead = (reap_fn)dlsym(dl, "rtpu_store_reap_dead");
  auto store_release_pid = (relpid_fn)dlsym(dl, "rtpu_store_release_pid");
  CHECK(store_open && store_create && store_seal && store_get);
  CHECK(store_create_noevict && store_lru_victim && store_reap_dead &&
        store_release_pid);

  // 1 MiB arena
  void* s = store_open(arena, 1 << 20, 1);
  CHECK(s != nullptr);

  // basic create/seal/get roundtrip
  int64_t off = store_create(s, "obj_a", 1000);
  CHECK(off > 0);
  CHECK(store_contains(s, "obj_a") == 0);  // not sealed yet
  CHECK(store_seal(s, "obj_a") == 0);
  CHECK(store_contains(s, "obj_a") == 1);
  uint64_t sz = 0;
  int64_t goff = store_get(s, "obj_a", &sz);
  CHECK(goff == off && sz == 1000);
  CHECK(store_create(s, "obj_a", 10) == -2);  // duplicate
  CHECK(store_release(s, "obj_a") == 0);

  // delete frees space
  uint64_t used0 = store_used(s);
  CHECK(store_delete(s, "obj_a") == 0);
  CHECK(store_used(s) == used0 - 1000);
  CHECK(store_contains(s, "obj_a") == 0);

  // eviction: fill the arena with unpinned objects, then demand more
  for (int i = 0; i < 7; i++) {
    char oid[32];
    snprintf(oid, sizeof oid, "fill_%d", i);
    CHECK(store_create(s, oid, 128 * 1024) > 0);
    CHECK(store_seal(s, oid) == 0);
  }
  // 7*128K = 896K used; another 256K must evict the two oldest
  CHECK(store_create(s, "big", 256 * 1024) > 0);
  CHECK(store_seal(s, "big") == 0);
  CHECK(store_contains(s, "fill_0") == 0);  // LRU-evicted
  CHECK(store_contains(s, "big") == 1);

  // pinned objects survive eviction pressure
  uint64_t bsz;
  CHECK(store_get(s, "big", &bsz) > 0);  // pin
  for (int i = 0; i < 10; i++) {
    char oid[32];
    snprintf(oid, sizeof oid, "press_%d", i);
    int64_t r = store_create(s, oid, 128 * 1024);
    if (r > 0) store_seal(s, oid);
  }
  CHECK(store_contains(s, "big") == 1);  // still pinned, never evicted
  CHECK(store_release(s, "big") == 0);

  // deferred delete: delete-while-pinned reclaims at release
  CHECK(store_create(s, "pinned", 1024) > 0);
  CHECK(store_seal(s, "pinned") == 0);
  CHECK(store_get(s, "pinned", &sz) > 0);
  CHECK(store_delete(s, "pinned") == 0);
  CHECK(store_contains(s, "pinned") == 0);      // gone from the index
  uint64_t used1 = store_used(s);
  CHECK(store_release(s, "pinned") == 0);       // space returns now
  CHECK(store_used(s) == used1 - 1024);

  // cross-process: child writes, parent reads the same arena
  pid_t pid = fork();
  if (pid == 0) {
    void* cs = store_open(arena, 1 << 20, 0);
    if (!cs) _exit(2);
    int64_t o = store_create(cs, "from_child", 64);
    if (o <= 0) _exit(3);
    if (store_seal(cs, "from_child") != 0) _exit(4);
    store_close(cs);
    _exit(0);
  }
  int status = 0;
  waitpid(pid, &status, 0);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  CHECK(store_contains(s, "from_child") == 1);

  // no-evict create: a full arena reports -1 instead of evicting; the LRU
  // victim query names the object an orchestrated spill would take
  for (int i = 0; i < 10; i++) {
    char oid[32];
    snprintf(oid, sizeof oid, "ne_fill_%d", i);
    int64_t r = store_create(s, oid, 128 * 1024);
    if (r > 0) store_seal(s, oid);
  }
  uint64_t used_before = store_used(s);
  CHECK(store_create_noevict(s, "ne_big", 256 * 1024) == -1);
  CHECK(store_used(s) == used_before);                        // nothing evicted
  CHECK(store_create_noevict(s, "ne_huge", 4ull << 20) == -4);  // > capacity
  char victim[48];
  int64_t off2;
  while ((off2 = store_create_noevict(s, "ne_big", 256 * 1024)) == -1) {
    CHECK(store_lru_victim(s, victim) == 0);
    CHECK(store_contains(s, victim) == 1);
    CHECK(store_delete(s, victim) == 0);  // what an orchestrated spill does
  }
  CHECK(off2 > 0);
  CHECK(store_seal(s, "ne_big") == 0);

  // dead-pid pin reaping: a child pins an object and exits WITHOUT
  // releasing; the parent reaps the orphaned pin so eviction can't wedge
  pid_t pinner = fork();
  if (pinner == 0) {
    void* cs = store_open(arena, 1 << 20, 0);
    if (!cs) _exit(2);
    uint64_t psz;
    if (store_get(cs, "ne_big", &psz) <= 0) _exit(3);
    _exit(0);  // dies holding the pin (no release, no close)
  }
  waitpid(pinner, &status, 0);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  CHECK(store_reap_dead(s) == 1);  // exactly the orphaned pin
  CHECK(store_reap_dead(s) == 0);  // idempotent

  // kCreating protection: a live writer's in-progress entry must not be
  // reclaimed by a concurrent create of the same id…
  CHECK(store_create(s, "inflight", 1024) > 0);
  CHECK(store_create(s, "inflight", 1024) == -2);
  CHECK(store_create_noevict(s, "inflight", 1024) == -2);
  CHECK(store_seal(s, "inflight") == 0);
  // …but a DEAD writer's unsealed entry is reclaimed and re-creatable
  pid_t creator = fork();
  if (creator == 0) {
    void* cs = store_open(arena, 1 << 20, 0);
    if (!cs) _exit(2);
    if (store_create(cs, "orphaned", 2048) <= 0) _exit(3);
    _exit(0);  // dies mid-put, entry left kCreating
  }
  waitpid(creator, &status, 0);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  CHECK(store_create(s, "orphaned", 2048) > 0);
  CHECK(store_seal(s, "orphaned") == 0);

  // release_pid: clean-exit bulk release of this process's pins
  uint64_t s1, s2;
  CHECK(store_get(s, "ne_big", &s1) > 0);
  CHECK(store_get(s, "ne_big", &s2) > 0);
  CHECK(store_release_pid(s, (int32_t)getpid()) == 2);
  uint64_t used2 = store_used(s);
  CHECK(store_delete(s, "ne_big") == 0);
  CHECK(store_used(s) == used2 - 256 * 1024);  // freed NOW → refs were 0

  store_close(s);
  unlink(arena);
  printf("shm_store_test: all checks passed\n");
  return 0;
}
