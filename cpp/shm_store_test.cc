// C-level test for the shm arena store: create/seal/get/release/delete,
// eviction under pressure, pin semantics, hole coalescing, multi-process
// sharing through fork. Exits 0 on success; any failed check aborts.
//
// Build+run (also driven by tests/test_shm_arena.py):
//   g++ -O2 -o shm_store_test shm_store_test.cc -ldl -lpthread && ./shm_store_test

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dlfcn.h>
#include <sys/wait.h>
#include <unistd.h>

typedef void* (*open_fn)(const char*, uint64_t, int);
typedef void (*close_fn)(void*);
typedef int64_t (*create_fn)(void*, const char*, uint64_t);
typedef int (*seal_fn)(void*, const char*);
typedef int64_t (*get_fn)(void*, const char*, uint64_t*);
typedef int (*rel_fn)(void*, const char*);
typedef int (*contains_fn)(void*, const char*);
typedef int (*del_fn)(void*, const char*);
typedef uint64_t (*used_fn)(void*);

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      return 1;                                                         \
    }                                                                   \
  } while (0)

int main(int argc, char** argv) {
  const char* libpath = argc > 1 ? argv[1] : "./libshmstore.so";
  const char* arena = argc > 2 ? argv[2] : "/dev/shm/rtpu_test_arena";
  unlink(arena);

  void* dl = dlopen(libpath, RTLD_NOW);
  CHECK(dl != nullptr);
  auto store_open = (open_fn)dlsym(dl, "rtpu_store_open");
  auto store_close = (close_fn)dlsym(dl, "rtpu_store_close");
  auto store_create = (create_fn)dlsym(dl, "rtpu_store_create");
  auto store_seal = (seal_fn)dlsym(dl, "rtpu_store_seal");
  auto store_get = (get_fn)dlsym(dl, "rtpu_store_get");
  auto store_release = (rel_fn)dlsym(dl, "rtpu_store_release");
  auto store_contains = (contains_fn)dlsym(dl, "rtpu_store_contains");
  auto store_delete = (del_fn)dlsym(dl, "rtpu_store_delete");
  auto store_used = (used_fn)dlsym(dl, "rtpu_store_used");
  CHECK(store_open && store_create && store_seal && store_get);

  // 1 MiB arena
  void* s = store_open(arena, 1 << 20, 1);
  CHECK(s != nullptr);

  // basic create/seal/get roundtrip
  int64_t off = store_create(s, "obj_a", 1000);
  CHECK(off > 0);
  CHECK(store_contains(s, "obj_a") == 0);  // not sealed yet
  CHECK(store_seal(s, "obj_a") == 0);
  CHECK(store_contains(s, "obj_a") == 1);
  uint64_t sz = 0;
  int64_t goff = store_get(s, "obj_a", &sz);
  CHECK(goff == off && sz == 1000);
  CHECK(store_create(s, "obj_a", 10) == -2);  // duplicate
  CHECK(store_release(s, "obj_a") == 0);

  // delete frees space
  uint64_t used0 = store_used(s);
  CHECK(store_delete(s, "obj_a") == 0);
  CHECK(store_used(s) == used0 - 1000);
  CHECK(store_contains(s, "obj_a") == 0);

  // eviction: fill the arena with unpinned objects, then demand more
  for (int i = 0; i < 7; i++) {
    char oid[32];
    snprintf(oid, sizeof oid, "fill_%d", i);
    CHECK(store_create(s, oid, 128 * 1024) > 0);
    CHECK(store_seal(s, oid) == 0);
  }
  // 7*128K = 896K used; another 256K must evict the two oldest
  CHECK(store_create(s, "big", 256 * 1024) > 0);
  CHECK(store_seal(s, "big") == 0);
  CHECK(store_contains(s, "fill_0") == 0);  // LRU-evicted
  CHECK(store_contains(s, "big") == 1);

  // pinned objects survive eviction pressure
  uint64_t bsz;
  CHECK(store_get(s, "big", &bsz) > 0);  // pin
  for (int i = 0; i < 10; i++) {
    char oid[32];
    snprintf(oid, sizeof oid, "press_%d", i);
    int64_t r = store_create(s, oid, 128 * 1024);
    if (r > 0) store_seal(s, oid);
  }
  CHECK(store_contains(s, "big") == 1);  // still pinned, never evicted
  CHECK(store_release(s, "big") == 0);

  // deferred delete: delete-while-pinned reclaims at release
  CHECK(store_create(s, "pinned", 1024) > 0);
  CHECK(store_seal(s, "pinned") == 0);
  CHECK(store_get(s, "pinned", &sz) > 0);
  CHECK(store_delete(s, "pinned") == 0);
  CHECK(store_contains(s, "pinned") == 0);      // gone from the index
  uint64_t used1 = store_used(s);
  CHECK(store_release(s, "pinned") == 0);       // space returns now
  CHECK(store_used(s) == used1 - 1024);

  // cross-process: child writes, parent reads the same arena
  pid_t pid = fork();
  if (pid == 0) {
    void* cs = store_open(arena, 1 << 20, 0);
    if (!cs) _exit(2);
    int64_t o = store_create(cs, "from_child", 64);
    if (o <= 0) _exit(3);
    if (store_seal(cs, "from_child") != 0) _exit(4);
    store_close(cs);
    _exit(0);
  }
  int status = 0;
  waitpid(pid, &status, 0);
  CHECK(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  CHECK(store_contains(s, "from_child") == 1);

  store_close(s);
  unlink(arena);
  printf("shm_store_test: all checks passed\n");
  return 0;
}
