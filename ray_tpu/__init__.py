"""ray_tpu — a TPU-native distributed AI framework.

Core API mirrors the reference's surface (init/remote/get/put/wait/kill,
actors, placement groups) while the compute path is pure JAX/XLA/Pallas over
TPU meshes. See SURVEY.md for the reference analysis this build follows.
"""

from ray_tpu._private.api import (
    available_resources,
    cancel,
    cluster_resources,
    cluster_state,
    free,
    get,
    get_actor,
    get_runtime_context,
    init,
    is_initialized,
    kill,
    nodes,
    timeline,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu._private.worker import ObjectRef
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.cross_lang import cpp_function, start_cpp_worker
from ray_tpu.remote_function import RemoteFunction
from ray_tpu import exceptions
from ray_tpu import util

__version__ = "0.1.0"

__all__ = [
    "ActorClass",
    "ActorHandle",
    "ObjectRef",
    "RemoteFunction",
    "available_resources",
    "cancel",
    "cluster_resources",
    "cluster_state",
    "cpp_function",
    "exceptions",
    "free",
    "get",
    "get_actor",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "timeline",
    "put",
    "remote",
    "shutdown",
    "start_cpp_worker",
    "wait",
    "util",
    "__version__",
]
