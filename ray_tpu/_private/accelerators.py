"""TPU accelerator detection and chip-isolation helpers.

(reference capability: python/ray/_private/accelerators/tpu.py —
`TPU_VISIBLE_CHIPS` per-worker isolation (:36), chips-per-host detection
(:100), GKE/GCE topology env detection (:17-65), and the pod-slice head
resource `TPU-{accelerator_type}-head` (:170, :529-534). Detection here is
env-var driven so tests can simulate topologies without hardware, matching
the reference's own test strategy — SURVEY.md §4.2.)
"""

from __future__ import annotations

import glob
import os

# The env var JAX/libtpu reads to restrict a process to a chip subset.
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
# Authoritative record of the chips the GCS bound to this worker process
# (set alongside TPU_VISIBLE_CHIPS at spawn; read back at registration).
WORKER_CHIPS_ENV = "RAY_TPU_WORKER_CHIPS"
# Opt-out: don't set TPU_VISIBLE_CHIPS on chip workers (reference:
# RAY_EXPERIMENTAL_NOSET_TPU_VISIBLE_CHIPS).
NOSET_VISIBLE_CHIPS_ENV = "RAY_TPU_NOSET_TPU_VISIBLE_CHIPS"


def detect_num_tpu_chips() -> int:
    """TPU chip count without importing jax (reference: tpu.py:100
    chips-per-host logic — there via GKE env vars / GCE metadata; here via
    env override or device files)."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env:
        return int(env)
    try:
        accel = glob.glob("/dev/accel*") + glob.glob("/dev/vfio/*")
        if accel:
            return len(accel)
    except OSError:
        pass
    return 0


def detect_tpu_labels() -> dict:
    """Topology labels for the node, from the same env vars GKE/GCE TPU VMs
    export (reference: tpu.py:17-65 — TPU_ACCELERATOR_TYPE, TPU_TOPOLOGY,
    TPU_NAME, TPU_WORKER_ID). These feed NodeLabel scheduling and the SLICE
    placement strategy."""
    labels = {}
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    if accel:
        labels["ray_tpu.io/accelerator-type"] = accel
    topo = os.environ.get("TPU_TOPOLOGY")
    if topo:
        labels["ray_tpu.io/tpu-topology"] = topo
    pod = os.environ.get("TPU_NAME")
    if pod:
        labels["ray_tpu.io/tpu-pod-name"] = pod
    wid = os.environ.get("TPU_WORKER_ID")
    if wid is not None and wid != "":
        labels["ray_tpu.io/tpu-worker-id"] = wid
    return labels


def tpu_head_resource_name(accelerator_type: str) -> str:
    """The per-slice rendezvous resource: exactly one unit on worker 0 of a
    pod slice, letting users schedule one coordinating actor per slice
    (reference: tpu.py:170,529-534 `TPU-{pod_type}-head`)."""
    return f"TPU-{accelerator_type}-head"


def head_resources() -> dict:
    """Extra resources this host contributes (the slice-head marker)."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    wid = os.environ.get("TPU_WORKER_ID", "0")
    if accel and wid == "0":
        return {tpu_head_resource_name(accel): 1.0}
    return {}


def detect_host_resources(num_cpus=None, num_tpus=None, resources=None,
                          labels=None) -> tuple[dict, dict]:
    """(total_resources, labels) for a host — shared by the head Node and
    follower NodeAgent so both advertise identically for the same hardware."""
    import os as _os

    total = {"CPU": float(num_cpus if num_cpus is not None
                          else (_os.cpu_count() or 1))}
    ntpu = num_tpus if num_tpus is not None else detect_num_tpu_chips()
    if ntpu:
        total["TPU"] = float(ntpu)
        total.update(head_resources())
    if resources:
        total.update({k: float(v) for k, v in resources.items()})
    merged_labels = {**detect_tpu_labels(), **(labels or {})}
    return total, merged_labels


def chips_required(resources: dict) -> int:
    """Whole chips a task/actor binds. Fractional TPU (<1) shares without
    isolation, like fractional GPU in the reference."""
    v = float(resources.get("TPU", 0.0))
    return int(v) if v >= 1.0 else 0


def validate_num_tpus(num_tpus) -> None:
    if num_tpus is not None and float(num_tpus) > 1 and float(num_tpus) != int(num_tpus):
        raise ValueError(
            f"num_tpus must be an integer when > 1 (got {num_tpus}): whole "
            f"chips are bound to a worker via TPU_VISIBLE_CHIPS")


def apply_chip_env(env: dict, chips: tuple | list) -> None:
    """Stamp a worker-spawn env with its chip binding (before any jax
    import in the child, so backend init only sees these chips)."""
    ids = ",".join(str(c) for c in chips)
    env[WORKER_CHIPS_ENV] = ids
    if os.environ.get(NOSET_VISIBLE_CHIPS_ENV) != "1":
        env[TPU_VISIBLE_CHIPS_ENV] = ids


def current_worker_chips() -> list[int]:
    """The chips the GCS bound to this worker process ([] for CPU workers)."""
    raw = os.environ.get(WORKER_CHIPS_ENV, "")
    return [int(c) for c in raw.split(",") if c != ""]
