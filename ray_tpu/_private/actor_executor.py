"""Per-actor execution engine: concurrency groups, threaded + async methods.

Reference capability: the core_worker task-execution stack —
`ConcurrencyGroupManager` routing methods to named thread pools
(src/ray/core_worker/task_execution/concurrency_group_manager.h), fiber
support for `async def` actor methods (fiber.h), and the per-actor
scheduling queues (actor_scheduling_queue.h).

Semantics:
- plain actor, max_concurrency=1 → methods run inline on the exec loop
  thread (strict ordering, as before);
- max_concurrency>1 → a default thread pool of that size;
- concurrency_groups={"name": limit} → one pool per group; methods pick a
  group via `@ray_tpu.method(concurrency_group="name")`, others use the
  default pool;
- `async def` methods → a dedicated asyncio event loop thread; the group
  limit is enforced with an asyncio.Semaphore per group, so thousands of
  coroutines can interleave on one loop (reference: async actors).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

from ray_tpu._private.constants import CONCURRENCY_GROUP_ATTR


def method_concurrency_group(instance, method_name: str) -> Optional[str]:
    fn = getattr(type(instance), method_name, None)
    return getattr(fn, CONCURRENCY_GROUP_ATTR, None)


class ActorExecutor:
    def __init__(self, instance, *, max_concurrency: int = 1,
                 concurrency_groups: Optional[Dict[str, int]] = None):
        self.instance = instance
        self.max_concurrency = max(1, int(max_concurrency))
        self.groups = {str(k): max(1, int(v))
                       for k, v in (concurrency_groups or {}).items()}
        self._pools: Dict[str, ThreadPoolExecutor] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._loop_started = threading.Event()
        self._sems: Dict[str, asyncio.Semaphore] = {}
        self._lock = threading.Lock()
        # async detection: any coroutine method on the class
        self.has_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(type(instance),
                                           predicate=inspect.isfunction))
        if self.has_async:
            t = threading.Thread(target=self._run_loop, daemon=True,
                                 name="actor-asyncio")
            t.start()
            self._loop_started.wait(10)

    # -- async plumbing ----------------------------------------------------

    def _run_loop(self):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        self._loop_started.set()
        loop.run_forever()

    def _sem_for(self, group: Optional[str]) -> asyncio.Semaphore:
        key = group or "_default"
        sem = self._sems.get(key)
        if sem is None:
            limit = (self.groups.get(group) if group else None) \
                or self.max_concurrency
            sem = self._sems[key] = asyncio.Semaphore(limit)
        return sem

    def run_coroutine_sync(self, coro):
        """Execute a coroutine on the actor's loop, blocking the calling
        thread until it resolves (used when execute_task runs an async
        method from a pool thread)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        return fut.result()

    # -- dispatch ----------------------------------------------------------

    def _pool_for(self, group: Optional[str]) -> Optional[ThreadPoolExecutor]:
        """Thread pool for sync methods (None → run inline, ordered)."""
        if group is not None and group in self.groups:
            with self._lock:
                pool = self._pools.get(group)
                if pool is None:
                    pool = self._pools[group] = ThreadPoolExecutor(
                        max_workers=self.groups[group],
                        thread_name_prefix=f"actor-{group}")
            return pool
        if self.max_concurrency > 1 or self.groups:
            with self._lock:
                pool = self._pools.get("_default")
                if pool is None:
                    pool = self._pools["_default"] = ThreadPoolExecutor(
                        max_workers=self.max_concurrency,
                        thread_name_prefix="actor-exec")
            return pool
        return None

    def submit(self, spec: dict, execute: Callable[[dict], None]) -> None:
        """Route one actor_task spec: async methods onto the event loop
        (bounded by their group's semaphore), sync methods onto their
        group's thread pool (or inline for plain actors)."""
        method_name = spec.get("method", "")
        fn = getattr(type(self.instance), method_name, None)
        group = getattr(fn, CONCURRENCY_GROUP_ATTR, None)
        if self.has_async and fn is not None and inspect.iscoroutinefunction(fn):
            sem = self._sem_for(group)

            async def bounded():
                async with sem:
                    # execute() resolves args and serializes results; the
                    # coroutine itself runs via run_coroutine_sync on THIS
                    # loop — so run execute in a thread to avoid blocking
                    # the loop on non-async work
                    await asyncio.get_event_loop().run_in_executor(
                        self._exec_pool(), execute, spec)

            asyncio.run_coroutine_threadsafe(bounded(), self._loop)
            return
        pool = self._pool_for(group)
        if pool is not None:
            pool.submit(execute, spec)
        else:
            execute(spec)

    def _exec_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            pool = self._pools.get("_async_exec")
            if pool is None:
                # one thread per admitted call: the GCS gates dispatch at the
                # total concurrency bound, so sizing the pool to that bound
                # guarantees every in-flight call owns a thread — a smaller
                # pool deadlocks coordination actors (a send() queued behind
                # blocked wait()ers would never run)
                width = self.max_concurrency + sum(self.groups.values())
                pool = self._pools["_async_exec"] = ThreadPoolExecutor(
                    max_workers=max(4, width), thread_name_prefix="actor-async")
            return pool

    def shutdown(self):
        for pool in self._pools.values():
            pool.shutdown(wait=False)
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
