"""Public API implementation: init/shutdown/remote/get/put/wait/kill.

(reference: python/ray/_private/worker.py — init:1427, shutdown:2072,
get:2821, plus the @ray.remote decorator plumbing.)
"""

from __future__ import annotations

import atexit
import inspect
import os
import threading
from typing import Any, Sequence

from ray_tpu._private.local_mode import LocalWorker
from ray_tpu._private.node import Node
from ray_tpu._private.worker import CoreWorker, ObjectRef, set_global_worker
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.exceptions import RayTpuError
from ray_tpu.remote_function import RemoteFunction

_lock = threading.RLock()
_node: Node | None = None
_worker = None  # CoreWorker | LocalWorker
_is_worker_process = False
_namespace_env_set = False  # init(namespace=...) exported the env var


def _get_worker():
    global _worker
    with _lock:
        if _worker is None:
            # inside a worker subprocess, the global CoreWorker is set by worker_main
            from ray_tpu._private.worker import _global_worker as gw

            if gw is not None:
                return gw
            init()
        return _worker


def is_initialized() -> bool:
    from ray_tpu._private.worker import _global_worker as gw

    return _worker is not None or gw is not None


def init(
    *,
    address: str | None = None,
    local_mode: bool = False,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict | None = None,
    num_workers: int = 0,
    max_workers: int = 16,
    ignore_reinit_error: bool = True,
    runtime_env: dict | None = None,
    namespace: str | None = None,
):
    """Start a new session, or join an existing one with `address=` (a GCS
    `host:port` / `unix:<path>`, or env RAY_TPU_ADDRESS — how submitted jobs
    and remote drivers attach; reference: ray.init(address=...)).
    Returns a context dict."""
    global _node, _worker
    with _lock:
        if _worker is not None:
            if ignore_reinit_error:
                return {"session_id": getattr(_node, "session_id", "local")}
            raise RayTpuError("ray_tpu already initialized")
        if local_mode or os.environ.get("RAY_TPU_LOCAL_MODE") == "1":
            _worker = LocalWorker()
            if namespace:
                _worker.namespace = namespace
            set_global_worker(None)
            return {"session_id": "local"}
        global _namespace_env_set
        if namespace:
            # the driver's namespace scopes its named actors; exported so
            # worker processes spawned for this session inherit it
            # (reference: ray.init(namespace=...))
            os.environ["RAY_TPU_NAMESPACE"] = namespace
            _namespace_env_set = True
        address = address or os.environ.get("RAY_TPU_ADDRESS")
        if address:
            _worker = CoreWorker(address, os.environ.get("RAY_TPU_SESSION"),
                                 kind="driver")
            if namespace:
                _worker.namespace = namespace
            if runtime_env:
                _worker.default_runtime_env = runtime_env
            atexit.register(shutdown)
            return {"session_id": _worker.session_id, "address": address}
        _node = Node(
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            num_workers=num_workers,
            max_workers=max_workers,
        )
        _worker = CoreWorker(_node.socket_path, _node.session_id, kind="driver")
        if namespace:
            _worker.namespace = namespace
        if runtime_env:
            # job-level default: every task/actor without its own runtime_env
            # inherits it (reference: ray.init(runtime_env=...))
            _worker.default_runtime_env = runtime_env
        atexit.register(shutdown)
        if num_workers:
            # block until the pre-spawned pool registers (slow interpreters on
            # small hosts otherwise make scheduling look nondeterministic)
            import time as _time

            deadline = _time.monotonic() + 60.0
            while _time.monotonic() < deadline:
                if _worker.cluster_state()["num_workers"] >= num_workers:
                    break
                # fine-grained poll: a 50ms step quantizes every session
                # start to multiples of it (worker boot is ~300-500ms, so
                # 10ms shaves a mean ~20-40ms off every init in the suite)
                _time.sleep(0.01)
        return {"session_id": _node.session_id, "session_dir": _node.session_dir}


def shutdown():
    global _node, _worker
    with _lock:
        if _worker is not None and isinstance(_worker, CoreWorker):
            _worker.disconnect()
        if _node is not None:
            _node.shutdown()
        _node = None
        _worker = None
        # don't leak this driver's namespace into the next init() in the
        # same process (test isolation) — but never clobber an env var the
        # USER exported themselves
        global _namespace_env_set
        if _namespace_env_set:
            os.environ.pop("RAY_TPU_NAMESPACE", None)
            _namespace_env_set = False
        # same isolation story for the node-drain notice: it names a node
        # of the session that just ended, and would read as a phantom
        # preemption to the next init()'s train sessions
        from ray_tpu._private.worker import _reset_drain

        _reset_drain()
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def remote(*args, **kwargs):
    """@remote decorator for functions and classes, with or without options."""

    def decorate(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **kwargs)
        return RemoteFunction(obj, **kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or inspect.isclass(args[0])):
        return decorate(args[0])
    if args:
        raise TypeError("@remote options must be keyword arguments")
    return decorate


def get(refs, *, timeout: float | None = None):
    if getattr(refs, "__dag_future__", False):
        # compiled-DAG futures (channel plane returns no ObjectRefs at all)
        return refs.result(timeout=timeout)
    if (isinstance(refs, (list, tuple))
            and any(getattr(r, "__dag_future__", False) for r in refs)):
        # lists may mix DAG futures and ObjectRefs; the timeout applies
        # per element (futures resolve in submission order anyway)
        return [get(r, timeout=timeout) for r in refs]
    return _get_worker().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    return _get_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1, timeout: float | None = None):
    if any(getattr(r, "__dag_future__", False) for r in refs):
        # channel-plane DAG futures have no ObjectRefs; poll their done()
        # (non-blocking) alongside ordinary refs with wait(timeout=0)
        import time as _time

        worker = _get_worker()
        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        want = min(num_returns, len(refs))
        while True:
            ready = [r for r in refs
                     if (r.done() if getattr(r, "__dag_future__", False)
                         else bool(worker.wait([r], num_returns=1,
                                               timeout=0)[0]))]
            if len(ready) >= want or (
                    deadline is not None
                    and _time.monotonic() >= deadline):
                ready = ready[:num_returns]
                ready_ids = {id(r) for r in ready}
                return ready, [r for r in refs if id(r) not in ready_ids]
            _time.sleep(0.005)
    return _get_worker().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    _get_worker().kill_actor(actor.actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    aid = _get_worker().get_named_actor(name, namespace=namespace)
    if aid is None:
        ns = namespace or _get_worker().namespace
        raise ValueError(f"no actor named {name!r} in namespace {ns!r}")
    return ActorHandle(aid)


def cancel(ref: ObjectRef, *, force: bool = False) -> bool:
    """Cancel the task that produces `ref` (reference: ray.cancel).
    Queued → dequeued, outputs raise a cancellation error. Running →
    interrupted only with force=True (the worker process is killed; the
    task is NOT retried)."""
    w = _get_worker()
    if not hasattr(w, "cancel_task"):
        return False  # local mode runs tasks synchronously
    return w.cancel_task(ref, force=force)


def free(refs: Sequence[ObjectRef]):
    _get_worker().free(refs)


def cluster_resources() -> dict:
    return _get_worker().cluster_state()["total_resources"]


def available_resources() -> dict:
    return _get_worker().cluster_state()["available_resources"]


def cluster_state() -> dict:
    return _get_worker().cluster_state()


def nodes() -> list:
    return _get_worker().list_nodes()


def timeline(filename: str | None = None) -> list:
    """All task events collected by the GCS (reference: ray.timeline() —
    with `filename`, a chrome://tracing JSON is written there too)."""
    w = _get_worker()
    events = (w.rpc({"type": "task_events"}).get("events", [])
              if hasattr(w, "rpc") else [])  # local mode keeps no store
    if hasattr(w, "rpc"):
        # cluster event log rides along as ctrl:<node> rows in the export
        events = events + w.rpc({"type": "list_events"}).get("events", [])
    if filename:
        # write even when empty: callers open the promised file next.
        # Actor rows labeled class/name, like `ray_tpu timeline`.
        from ray_tpu._private.task_events import (export_chrome_trace,
                                                  fetch_worker_names)

        export_chrome_trace(events, filename,
                            fetch_worker_names(w.rpc)
                            if hasattr(w, "rpc") else {})
    return events


class RuntimeContext:
    """(reference: ray.runtime_context.RuntimeContext — ids, namespace,
    accelerator assignment for the calling task/actor/driver.)"""

    def __init__(self, worker):
        self._w = worker

    @property
    def was_current_actor_restarted(self):
        return False

    def get_actor_id(self):
        return getattr(self._w, "current_actor_id", None)

    def get_task_id(self):
        return getattr(self._w, "current_task_id", None)

    def get_worker_id(self):
        return getattr(self._w, "wid", None)

    def get_node_id(self):
        return getattr(self._w, "node_id", "node-0")

    def get_job_id(self):
        return os.environ.get("RAY_TPU_JOB_ID") or getattr(
            self._w, "session_id", None)

    @property
    def namespace(self) -> str:
        eff = getattr(self._w, "effective_namespace", None)
        return eff() if callable(eff) else getattr(
            self._w, "namespace", "default")

    def get_accelerator_ids(self) -> dict:
        """Chips the scheduler granted THIS process (reference:
        get_accelerator_ids / get_gpu_ids). Reads the GCS's own binding
        env, which is set regardless of the TPU_VISIBLE_CHIPS opt-out."""
        from ray_tpu._private import accelerators

        return {"TPU": [str(c) for c in accelerators.current_worker_chips()]}

    def get_placement_group_id(self):
        """The PG the CURRENT task was scheduled into, if any (stashed
        from the executing spec's scheduling strategy)."""
        ctx = getattr(self._w, "_task_ctx", None)
        return getattr(ctx, "pg_id", None) if ctx is not None else None


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_get_worker())
