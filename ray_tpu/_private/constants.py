"""Shared cross-process protocol constants.

Names that cross a process boundary — shm segment prefixes, named-actor
name schemes, magic actor-task method names — must come from ONE module:
a producer and a consumer compiled from different call sites can never
drift apart, and the `graft_check` static suite (tools/graft_check)
enforces that these strings are never re-spelled as literals elsewhere
in the package.

(reference: ray_constants.py / src/ray/common/constants.h — the reference
keeps every wire-visible magic string in one constants module for the
same reason.)
"""

from __future__ import annotations

# ---------------------------------------------------------------- shm names

#: tmpfs directory all shm segments live in (channels, arenas, spill).
SHM_DIR = "/dev/shm"

#: every per-session shm object (arena segment, file-backed object store
#: entries) is named f"{SHM_SESSION_PREFIX}{session_id}_..." — leak checks
#: and teardown sweeps key on this prefix.
SHM_SESSION_PREFIX = "rtpu_"

#: mutable seqlock channel segments (compiled-DAG edges, PD KV transfer):
#: f"{SHM_CHANNEL_PREFIX}{uuid}" under SHM_DIR. Teardown leak checks glob
#: SHM_CHANNEL_GLOB and must agree with the creator's naming.
SHM_CHANNEL_PREFIX = "rtpu_chan_"

#: glob matching every live channel segment (teardown/leak sweeps).
SHM_CHANNEL_GLOB = SHM_DIR + "/" + SHM_CHANNEL_PREFIX + "*"

#: serve routing-table broadcast segments (single writer = the serve
#: controller, many readers = the proxy shards): f"{SHM_ROUTING_PREFIX}{nonce}"
#: under SHM_DIR. The controller creates/unlinks the segment with the proxy
#: plane's lifecycle; chaos leak checks glob SHM_ROUTING_GLOB.
SHM_ROUTING_PREFIX = "rtpu_routes_"

#: glob matching every live routing-table segment (teardown/leak sweeps).
SHM_ROUTING_GLOB = SHM_DIR + "/" + SHM_ROUTING_PREFIX + "*"

# ----------------------------------------------------- cross-process methods

#: actor-task method name the worker routes to the compiled-DAG channel
#: exec loop (ray_tpu/dag/channel_execution.py) on a dedicated thread —
#: the spec producer (driver) and the worker dispatcher share this one
#: definition. Re-exported by task_spec.py for back-compat.
EXEC_LOOP_METHOD = "__ray_tpu_channel_exec_loop__"

#: function attribute `@ray_tpu.method(concurrency_group=...)` stamps on a
#: method and the actor executor / GCS create-spec introspection read back.
CONCURRENCY_GROUP_ATTR = "__ray_tpu_concurrency_group__"

#: function attribute `@ray_tpu.method(tensor_transport=...)` stamps; the
#: worker's result-serialization path reads it to route device tensors.
TENSOR_TRANSPORT_ATTR = "__ray_tpu_tensor_transport__"

# ------------------------------------------------------------- named actors

#: the serve controller's named-actor name (namespace "_system").
SERVE_CONTROLLER_NAME = "SERVE_CONTROLLER"

#: serve replica actors are named
#: f"{SERVE_REPLICA_NAME_PREFIX}{full_name}:{tag}:{nonce}" (namespace
#: "_system") — the controller's crash-recovery re-adopts replicas by
#: exactly this name, so creator and recovery must share the scheme.
SERVE_REPLICA_NAME_PREFIX = "SERVE_REPLICA:"

#: sharded proxy-plane workers are named
#: f"{SERVE_PROXY_NAME_PREFIX}{index}:{nonce}:{gen}" (namespace "_system") —
#: the controller starts, health-checks, replaces, and crash-recovery
#: re-adopts proxy shards by exactly this name, mirroring the replica scheme
#: above. `gen` is a plane-wide generation counter persisted BEFORE each
#: create: a SIGKILLed shard can hold its name past its death, so a
#: replacement must never reuse it.
SERVE_PROXY_NAME_PREFIX = "SERVE_PROXY:"

#: request-envelope key carrying a zero-copy body reference: when an HTTP
#: body exceeds RayConfig.serve_zero_copy_threshold_bytes the proxy `put`s
#: the raw bytes into the arena object plane and ships the object id hex
#: under this key instead of pickling the body through fast-RPC; the replica
#: unwraps it before user code runs. Producer (proxy) and consumer (replica)
#: live in different processes, so the key is wire protocol.
SERVE_BODY_REF_KEY = "__rtpu_body_ref__"

# ---------------------------------------------------------------- mesh axes

#: the SPMD mesh-axis vocabulary. These strings are program-wide protocol:
#: a collective's `axis_name`, a `PartitionSpec` entry, and the mesh
#: construction in parallel/mesh.py must all agree, and a typo'd axis only
#: explodes at runtime on the real device mesh. The `spmd-consistency`
#: static check resolves every axis string in train/, parallel/, ops/ and
#: llm/ against MESH_AXES, so drift fails tier-1 instead of a TPU job.
MESH_AXIS_DP = "dp"        # data parallel (gradient psum)
MESH_AXIS_FSDP = "fsdp"    # fully-sharded data parallel
MESH_AXIS_EP = "ep"        # expert parallel (MoE)
MESH_AXIS_PP = "pp"        # pipeline parallel (layer stages)
MESH_AXIS_SP = "sp"        # sequence/context parallel (ring attention)
MESH_AXIS_TP = "tp"        # tensor parallel (heads / mlp / vocab)

#: canonical mesh-axis order, outermost→innermost (tp innermost so its
#: collectives ride the shortest ICI hops).
MESH_AXES = (MESH_AXIS_DP, MESH_AXIS_FSDP, MESH_AXIS_EP, MESH_AXIS_PP,
             MESH_AXIS_SP, MESH_AXIS_TP)

# ------------------------------------------------------------------ metrics

#: canonical exported-metric namespace (tools/graft_check metric-name check).
METRIC_NAME_PREFIX = "ray_tpu_"

# ------------------------------------------------------------- node drain

#: GCS RPC type that marks a node DRAINING (scheduler stops placing there,
#: resident workers get a `drain_notice` push, the autoscaler
#: drains-then-terminates). Documented here as protocol; RPC call sites and
#: the gcs.py dispatch arm spell the literal so the rpc-pairing /
#: rpc-field-schema checkers can pair them lexically.
NODE_DRAIN_RPC = "node_drain"

#: unsolicited GCS→worker/agent push announcing the worker's node is
#: draining; CoreWorker._recv_loop records it and train sessions read it as
#: the "save a preemption-grace checkpoint now" flag.
DRAIN_NOTICE_PUSH = "drain_notice"

#: node lifecycle state names surfaced by list_nodes / cluster_state and by
#: the autoscaler instance state machine's DRAINING state — one vocabulary
#: across the GCS node table and the instance table.
NODE_STATE_ALIVE = "ALIVE"
NODE_STATE_DRAINING = "DRAINING"
NODE_STATE_DEAD = "DEAD"

#: TrainWorker.poll() payload keys for cooperative-stop acknowledgement and
#: per-step progress heartbeats: producer (train/worker_group.py) and
#: consumer (train/controller.py hang watchdog) live in different
#: processes, so the keys are wire protocol. Progress rides as an AGE
#: (seconds since the rank's last session.report), not a timestamp —
#: controller and worker clocks need not agree.
TRAIN_POLL_STOP_OBSERVED = "stop_observed"
TRAIN_POLL_PROGRESS_AGE = "progress_age_s"

# ------------------------------------------------------------ cluster events
#
# The structured cluster event log (_private/events.py + the GCS ring).
# Event-type and severity strings cross process boundaries twice: once on
# the `cluster_events_report` flush from controller processes to the GCS,
# and again on every `list_events` read (CLI, state API, dashboard). A
# producer spelling "node.leave" and a filter spelling "node.left" would
# silently match nothing, so the whole vocabulary lives here and the
# `event-type-literal` graft_check forbids re-spelled literals at
# emit_event() call sites outside this module.

#: GCS RPC type flushing a batch of locally-buffered cluster events (serve
#: controller, train controller — anything not co-resident with the GCS).
#: Documented here as protocol; call sites and the gcs.py dispatch arm
#: spell the literal so the rpc-pairing checker can pair them lexically.
CLUSTER_EVENTS_RPC = "cluster_events_report"

#: GCS RPC type reading the event ring with server-side limit/severity/
#: type/node filtering (same lexical-literal discipline as above).
LIST_EVENTS_RPC = "list_events"

#: GCS RPC type answering "why is X pending" with the live per-node
#: rejection table for a pending actor or placement group.
SCHED_EXPLAIN_RPC = "sched_explain"

#: severity vocabulary, orderable by index in EVENT_SEVERITIES.
EVENT_SEVERITY_DEBUG = "DEBUG"
EVENT_SEVERITY_INFO = "INFO"
EVENT_SEVERITY_WARNING = "WARNING"
EVENT_SEVERITY_ERROR = "ERROR"
EVENT_SEVERITIES = (EVENT_SEVERITY_DEBUG, EVENT_SEVERITY_INFO,
                    EVENT_SEVERITY_WARNING, EVENT_SEVERITY_ERROR)

#: event-type vocabulary: "<entity>.<transition>". Every type a producer
#: may emit is enumerated here — `ray_tpu events --type` completion, the
#: README taxonomy table, and the dashboard all key on these strings.
EVENT_NODE_JOIN = "node.join"
EVENT_NODE_LEAVE = "node.leave"
EVENT_NODE_DRAIN = "node.drain"
EVENT_ACTOR_PENDING = "actor.pending"
EVENT_ACTOR_ALIVE = "actor.alive"
EVENT_ACTOR_RESTARTING = "actor.restarting"
EVENT_ACTOR_DEAD = "actor.dead"
EVENT_PG_PENDING = "pg.pending"
EVENT_PG_CREATED = "pg.created"
EVENT_PG_REMOVED = "pg.removed"
EVENT_LEASE_GRANT = "lease.grant"
EVENT_LEASE_RELEASE = "lease.release"
EVENT_AUTOSCALER_INSTANCE = "autoscaler.instance"
EVENT_SERVE_RECONCILE = "serve.reconcile"
EVENT_TRAIN_ATTEMPT = "train.attempt"
#: data-plane fault tolerance: a block's task was resubmitted after a
#: SYSTEM error (actor death / worker crash / lost object), a dead
#: `_MapPoolActor` was replaced by pool supervision, or a block was
#: permanently errored (UDF raise under the skip policy, or a retry
#: budget exhausted).
EVENT_DATA_BLOCK_RETRY = "data.block_retry"
EVENT_DATA_ACTOR_REPLACED = "data.actor_replaced"
EVENT_DATA_BLOCK_ERRORED = "data.block_errored"

EVENT_TYPES = (
    EVENT_NODE_JOIN, EVENT_NODE_LEAVE, EVENT_NODE_DRAIN,
    EVENT_ACTOR_PENDING, EVENT_ACTOR_ALIVE, EVENT_ACTOR_RESTARTING,
    EVENT_ACTOR_DEAD,
    EVENT_PG_PENDING, EVENT_PG_CREATED, EVENT_PG_REMOVED,
    EVENT_LEASE_GRANT, EVENT_LEASE_RELEASE,
    EVENT_AUTOSCALER_INSTANCE, EVENT_SERVE_RECONCILE, EVENT_TRAIN_ATTEMPT,
    EVENT_DATA_BLOCK_RETRY, EVENT_DATA_ACTOR_REPLACED,
    EVENT_DATA_BLOCK_ERRORED,
)

#: canonical field names on the event record envelope. Producers populate
#: them positionally through emit_event()'s signature; consumers (CLI
#: column layout, dashboard JSON, chrome-trace row mapping) index by these.
EVENT_FIELD_SEQ = "seq"
EVENT_FIELD_TS = "ts"
EVENT_FIELD_TYPE = "etype"
EVENT_FIELD_SEVERITY = "severity"
EVENT_FIELD_SOURCE = "source"
EVENT_FIELD_NODE = "node"
EVENT_FIELD_MESSAGE = "message"

#: pytest marker gating the data-plane chaos suite (SIGKILL of pool
#: actors / forced block loss mid-pipeline). Registered in pytest.ini and
#: spelled by tests/test_data_chaos.py's module pytestmark.
DATA_CHAOS_MARKER = "data_chaos"

# ---------------------------------------------------------------- deadlines

#: HTTP request header carrying the per-request deadline budget in seconds
#: (float). The proxy converts it to an absolute wall-clock deadline that
#: rides the request-context envelope through handle → replica → engine;
#: every hop refuses work it can no longer finish. Clients and the
#: load-bench speak this exact header, so it is wire protocol.
HTTP_DEADLINE_HEADER = "x-ray-tpu-deadline-s"
