"""Direct task dispatch: lease-based caller→worker submission.

The GCS grants a caller a *lease* on an idle worker; task specs then flow
directly caller→worker over a dedicated connection, and results flow straight
back — the central scheduler is off the per-task hot path entirely. Plain
tasks with ready dependencies ride this plane; anything needing cluster-level
decisions (placement strategies, queuing, actor state, streaming) stays on
the GCS path, and a failed lease attempt falls back to it too (spillback).

Locality: the caller targets its lease request at the host holding a task's
largest dependency, so big arguments never cross hosts.

(reference: src/ray/core_worker/task_submission/normal_task_submitter.h:81 —
lease request + direct task push with pipelining; lease_policy.h —
locality-aware lease targeting; src/ray/raylet/scheduling/
cluster_lease_manager.h:41 — lease grant/spillback. The reference leases
from per-node raylets; here the GCS arbitrates grants but task bytes never
touch it.)
"""

from __future__ import annotations

import collections
import os
import socket
import threading
import time

from ray_tpu._private.protocol import (ConnectionClosed, MsgConnection,
                                       connect_address, listen_tcp)

# per-lease submission pipeline depth (reference: max_tasks_in_flight_per_worker)
MAX_INFLIGHT = 16
# how long a lease may sit unused at the caller before being returned
LEASE_IDLE_S = 2.0
# min delay between failed lease attempts for one shape (exponential to _MAX)
LEASE_RETRY_MIN_S = 0.02
LEASE_RETRY_MAX_S = 1.0


def shape_key(resources: dict, renv_hash: str) -> tuple:
    return (tuple(sorted((resources or {}).items())), renv_hash)


class DirectServer:
    """Worker-side: accepts leased-caller connections and executes specs.

    One caller connection is active per lease. A recv thread parses frames
    and feeds a local queue; a single exec thread drains it in order, so
    queued-but-unstarted tasks can be cancelled out of the queue while a
    long task runs (reference: ray.cancel dequeues leased-worker tasks)."""

    def __init__(self, core):
        self.core = core
        adv = os.environ.get("RAY_TPU_HOST_IP", "127.0.0.1")
        self.sock = listen_tcp("0.0.0.0", 0)
        self.address = f"{adv}:{self.sock.getsockname()[1]}"
        self._stopped = False
        # small result cache so a chained task submitted to the same lease can
        # resolve its predecessor's output without any GCS hop
        self.recent: collections.OrderedDict[str, tuple] = collections.OrderedDict()
        self.recent_cap = 4096
        self._accept_thread = threading.Thread(
            target=self._accept, daemon=True, name="direct-accept")
        self._accept_thread.start()

    def _accept(self):
        while not self._stopped:
            try:
                s, _ = self.sock.accept()
            except OSError:
                return
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve, args=(MsgConnection(s),),
                             daemon=True, name="direct-serve").start()

    def note_recent(self, oid: str, where: str, inline, is_error: bool) -> None:
        self.recent[oid] = (where, inline, is_error)
        while len(self.recent) > self.recent_cap:
            self.recent.popitem(last=False)

    def _serve(self, conn: MsgConnection):
        import queue as _q

        core = self.core
        queue: collections.deque = collections.deque()
        wakeups: _q.SimpleQueue = _q.SimpleQueue()  # C-level block/wake
        cancelled: set[str] = set()
        running: list = [None]  # task_id of the spec being executed
        closed = threading.Event()
        token = [None]

        # replies coalesce while more work is queued: one frame (and one
        # caller wakeup) covers a whole pipelined burst — the dominant cost
        # per trivial task is syscalls + context switches, not work. A 1 ms
        # micro-flusher bounds reply latency so a buffered fast result never
        # waits behind a long-running successor.
        out: list = []
        out_lock = threading.Lock()
        out_event = threading.Event()

        def flush() -> bool:
            with out_lock:
                batch, out[:] = list(out), []
            if not batch:
                return True
            try:
                if len(batch) == 1:
                    conn.send({"rid": batch[0][0], "done": batch[0][1]})
                else:
                    conn.send({"dones": batch})
            except ConnectionClosed:
                return False
            return True

        def flusher_loop():
            while not closed.is_set():
                out_event.wait(0.5)
                out_event.clear()
                if closed.is_set():
                    return
                time.sleep(0.001)
                flush()

        def exec_loop():
            while True:
                wakeups.get()
                if closed.is_set() and not queue:
                    flush()
                    return
                try:
                    rid, spec = queue.popleft()
                except IndexError:
                    if not queue and not flush():
                        return
                    continue  # its spec was cancelled out of the queue
                tid = spec["task_id"]
                if tid in cancelled:
                    cancelled.discard(tid)
                    if not queue and not flush():
                        return
                    continue  # cancel reply already sent by the recv side
                running[0] = tid
                done = core.execute_spec(spec)
                running[0] = None
                core.register_direct_results(spec, done, self)
                with out_lock:
                    out.append((rid, {k: done.get(k) for k in
                                      ("task_id", "results", "error",
                                       "contained", "published")}))
                    n_out = len(out)
                if n_out == 1:
                    out_event.set()  # arm the micro-flusher
                if queue and n_out < 32:
                    continue
                if not flush():
                    return

        exec_thread = threading.Thread(target=exec_loop, daemon=True,
                                       name="direct-exec")
        exec_thread.start()
        threading.Thread(target=flusher_loop, daemon=True,
                         name="direct-flush").start()
        try:
            while True:
                msg = conn.recv()
                t = msg.get("type")
                if t == "exec_direct":
                    if msg.get("token") is not None:
                        token[0] = msg["token"]
                    queue.append((msg["rid"], msg["spec"]))
                    wakeups.put(1)
                elif t == "exec_direct_batch":
                    if msg.get("token") is not None:
                        token[0] = msg["token"]
                    for rid_spec in msg["items"]:
                        queue.append(rid_spec)
                        wakeups.put(1)
                elif t == "cancel_direct":
                    tid = msg["task_id"]
                    hit = False
                    for item in list(queue):
                        if item[1]["task_id"] == tid:
                            try:
                                queue.remove(item)
                            except ValueError:
                                break  # exec thread won the race
                            cancelled.add(tid)
                            try:
                                conn.send({"rid": item[0], "done": {
                                    "task_id": tid, "cancelled": True}})
                            except ConnectionClosed:
                                pass
                            hit = True
                            break
                    if not hit and running[0] == tid and msg.get("force"):
                        # force-cancel the running task: this process dies
                        # (reference: force-cancelled tasks kill the executor)
                        try:
                            conn.send({"rid": msg["rid"], "cancelled": True})
                        except ConnectionClosed:
                            pass
                        os._exit(1)
                    try:
                        conn.send({"rid": msg["rid"], "cancelled": hit})
                    except ConnectionClosed:
                        pass
                elif t == "bye":
                    break
        except ConnectionClosed:
            pass
        finally:
            closed.set()
            wakeups.put(1)
            out_event.set()
            exec_thread.join(timeout=300.0)
            try:
                conn.close()
            except Exception:
                pass
            # tell the GCS this lease ended (idempotent: token-guarded); the
            # clean `bye` path also sends return_lease from the caller, and
            # whichever lands first wins
            if token[0] is not None:
                try:
                    core.send_no_reply({"type": "lease_released",
                                        "wid": core.wid, "token": token[0]})
                except Exception:
                    pass

    def stop(self):
        self._stopped = True
        try:
            self.sock.close()
        except OSError:
            pass


class _Lease:
    __slots__ = ("wid", "addr", "host", "node", "token", "conn", "inflight",
                 "last_used", "last_done", "dead", "draining", "key", "lock",
                 "death_reason")

    def __init__(self, wid, addr, host, node, token, conn, key):
        self.wid = wid
        self.addr = addr
        self.host = host
        self.node = node
        self.token = token
        self.conn = conn
        self.key = key
        self.inflight: dict[str, dict] = {}  # task_id → spec
        self.last_used = time.monotonic()
        self.last_done = 0.0
        self.dead = False
        self.draining = False
        self.death_reason: str | None = None
        self.lock = threading.Lock()

    def cap(self, now: float) -> int:
        """Adaptive pipeline depth: pipeline deep only while the worker is
        visibly turning tasks over; behind a long-running task, cap at 1 so
        waiting work stays schedulable elsewhere (and visible as backlog —
        reference: work is stolen back from slow leased workers)."""
        if now - self.last_done <= 0.25:
            return MAX_INFLIGHT  # short-task regime: completions are fresh
        return 1


class DirectDispatcher:
    """Caller-side lease pool, local submission queue, and direct pushes.

    Specs that pass eligibility but find no lease headroom wait in a local
    per-shape queue (reference: the submitter queues tasks awaiting leases)
    and are pumped onto leases as replies drain. If the pool for a shape
    vanishes, queued specs are re-routed to the GCS path."""

    QUEUE_CAP = 4096

    def __init__(self, core):
        self.core = core
        self.lock = threading.RLock()
        self.leases: dict[tuple, list[_Lease]] = {}
        self.by_wid: dict[str, _Lease] = {}
        self.local_queue: dict[tuple, collections.deque] = {}
        self._next_try: dict[tuple, float] = {}
        self._backoff: dict[tuple, float] = {}
        import itertools

        self._rid = itertools.count(1)  # next() is atomic under the GIL
        self._pending: dict[int, object] = {}  # rid → _Future for cancels
        self.submitted = 0  # stats (tests assert the fast path engaged)
        self._maint = threading.Thread(target=self._maintenance_loop,
                                       daemon=True, name="direct-maint")
        self._maint.start()

    def _maintenance_loop(self):
        # lease upkeep runs on its OWN thread: _grow blocks on a GCS RPC and
        # must never stall the caller's refcount-flush cadence
        while getattr(self.core, "_alive", True):
            time.sleep(0.2)
            try:
                self.reap_idle()
            except Exception:
                pass

    # ------------------------------------------------------------ leasing

    def _grow(self, key: tuple, resources: dict, renv_hash: str,
              prefer_host: str | None) -> None:
        now = time.monotonic()
        with self.lock:
            if now < self._next_try.get(key, 0.0):
                return
            # optimistic: push the next attempt out before dropping the lock
            self._next_try[key] = now + self._backoff.get(key, LEASE_RETRY_MIN_S)
        try:
            # pool width tracks the machine: on small boxes extra worker
            # processes just contend for the same cores
            count = max(2, min(4, os.cpu_count() or 1))
            with self.lock:
                backlog = len(self.local_queue.get(key) or ())
            reply = self.core.rpc({"type": "lease_workers",
                                   "resources": dict(resources or {}),
                                   "renv_hash": renv_hash, "count": count,
                                   "backlog": backlog,
                                   "prefer_host": prefer_host}, timeout=30.0)
        except Exception:
            return
        grants = reply.get("leases") or ()
        with self.lock:
            if grants:
                self._backoff[key] = LEASE_RETRY_MIN_S
                self._next_try[key] = 0.0
            else:
                self._backoff[key] = min(
                    LEASE_RETRY_MAX_S,
                    self._backoff.get(key, LEASE_RETRY_MIN_S) * 2)
                self._next_try[key] = time.monotonic() + self._backoff[key]
        for g in grants:
            try:
                conn = connect_address(g["addr"], timeout=10.0)
            except (OSError, ConnectionClosed):
                # worker unreachable: hand the lease straight back
                try:
                    self.core.send_no_reply(
                        {"type": "return_lease",
                         "tokens": {g["wid"]: g["token"]}})
                except Exception:
                    pass
                continue
            lease = _Lease(g["wid"], g["addr"], g["host"], g["node"],
                           g["token"], conn, key)
            with self.lock:
                self.leases.setdefault(key, []).append(lease)
                self.by_wid[lease.wid] = lease
            threading.Thread(target=self._recv_loop, args=(lease,),
                             daemon=True, name="direct-recv").start()
        if grants:
            self.pump(key)

    def _candidates(self, key: tuple, host: str | None = None) -> list[_Lease]:
        """Live leases for `key` with pipeline headroom (optionally on one
        host). Takes and releases self.lock."""
        now = time.monotonic()
        with self.lock:
            return [l for l in self.leases.get(key, ())
                    if not l.dead and not l.draining
                    and (host is None or l.host == host)
                    and len(l.inflight) < l.cap(now)]

    def pick(self, key: tuple, resources: dict, renv_hash: str,
             prefer_host: str | None) -> _Lease | None:
        """A lease with pipeline headroom, preferring `prefer_host`."""
        cands = self._candidates(key)
        if not cands:
            self._grow(key, resources, renv_hash, prefer_host)
            cands = self._candidates(key)
            if not cands:
                return None
        if prefer_host is not None:
            local = [l for l in cands if l.host == prefer_host]
            if local:
                cands = local
            else:
                # no lease on the preferred host yet: try to get one there
                self._grow(key, resources, renv_hash, prefer_host)
                fresh = self._candidates(key, host=prefer_host)
                if fresh:
                    cands = fresh
        return min(cands, key=lambda l: len(l.inflight))

    # --------------------------------------------------------- submission

    def submit_or_queue(self, key: tuple, spec: dict, resources: dict,
                        renv_hash: str, prefer_host: str | None,
                        required_lease: "_Lease | None") -> bool:
        """Park the spec in the local queue (coalesced sends — frame
        syscalls, not task work, dominate trivial tasks); pump when a burst
        accumulates. Locality-targeted specs ship immediately instead.
        False → caller should use the GCS path."""
        if prefer_host is not None and required_lease is None:
            # big-dep task: route straight at the dep's host
            lease = self.pick(key, resources, renv_hash, prefer_host)
            if lease is not None:
                return self._send(lease, spec)
        if required_lease is not None:
            if required_lease.dead:
                return False
            if not self._enqueue(key, spec, required_lease.wid):
                return False
        else:
            with self.lock:
                live = any(not l.dead for l in self.leases.get(key, ()))
            if not live:
                self._grow(key, resources, renv_hash, prefer_host)
                with self.lock:
                    live = any(not l.dead for l in self.leases.get(key, ()))
                if not live:
                    return False
            if not self._enqueue(key, spec, None):
                return False
        with self.lock:
            depth = len(self.local_queue.get(key, ()))
        if depth >= MAX_INFLIGHT:
            self.pump(key)
        return True

    def flush(self) -> None:
        """Push every queued spec out now — called when the caller is about
        to block on results."""
        with self.lock:
            keys = [k for k, q in self.local_queue.items() if q]
        for key in keys:
            self.pump(key)

    def _enqueue(self, key: tuple, spec: dict, pin: str | None) -> bool:
        with self.lock:
            q = self.local_queue.setdefault(key, collections.deque())
            if len(q) >= self.QUEUE_CAP:
                return False
            q.append((spec, pin))
        return True

    def _send(self, lease: _Lease, spec: dict) -> bool:
        rid = next(self._rid)
        with lease.lock:
            if lease.dead:
                return False
            lease.inflight[spec["task_id"]] = spec
            lease.last_used = time.monotonic()
        self.core._note_direct_lease(spec, lease.wid)
        try:
            lease.conn.send({"type": "exec_direct", "rid": rid, "spec": spec,
                             "token": lease.token})
        except ConnectionClosed:
            with lease.lock:
                lease.inflight.pop(spec["task_id"], None)
            self._fail_lease(lease)
            return False
        self.submitted += 1
        return True

    def _send_batch(self, lease: _Lease, specs: list[dict]) -> bool:
        items = []
        with lease.lock:
            if lease.dead:
                return False
            for spec in specs:
                items.append((next(self._rid), spec))
                lease.inflight[spec["task_id"]] = spec
            lease.last_used = time.monotonic()
        for spec in specs:
            self.core._note_direct_lease(spec, lease.wid)
        try:
            lease.conn.send({"type": "exec_direct_batch", "items": items,
                             "token": lease.token})
        except ConnectionClosed:
            with lease.lock:
                for spec in specs:
                    lease.inflight.pop(spec["task_id"], None)
            self._fail_lease(lease)
            return False
        self.submitted += len(specs)
        return True

    def pump(self, key: tuple) -> None:
        """Drain the local queue onto leases with headroom (FIFO). Runs of
        compatible specs ship as ONE frame per lease (syscalls, not task
        work, dominate trivial-task cost)."""
        while True:
            route_to_gcs = None
            lease = None
            batch: list[tuple] = []
            with self.lock:
                q = self.local_queue.get(key)
                if not q:
                    return
                spec, pin = q[0]
                now = time.monotonic()
                if pin is not None:
                    l = self.by_wid.get(pin)
                    if l is None or l.dead:
                        q.popleft()
                        route_to_gcs = spec  # pinned lease died before send
                    elif len(l.inflight) < MAX_INFLIGHT:
                        # chains must stay put: ignore the adaptive cap
                        lease = l  # draining is fine too
                    else:
                        return  # head is blocked on its pinned lease
                else:
                    cands = [l for l in self.leases.get(key, ())
                             if not l.dead and not l.draining
                             and len(l.inflight) < l.cap(now)]  # under lock
                    if not cands:
                        return
                    lease = min(cands, key=lambda l: len(l.inflight))
                if lease is not None:
                    room = (MAX_INFLIGHT if pin is not None
                            else lease.cap(now)) - len(lease.inflight)
                    while q and room > 0:
                        spec, pin = q[0]
                        if pin is not None and pin != lease.wid:
                            break  # next item needs a different lease
                        q.popleft()
                        batch.append((spec, pin))
                        room -= 1
            if route_to_gcs is not None:
                self.core._redirect_to_gcs(route_to_gcs)
                continue
            if not batch:
                return
            if not self._send_batch(lease, [s for s, _ in batch]):
                with self.lock:
                    q = self.local_queue.setdefault(key, collections.deque())
                    for item in reversed(batch):
                        q.appendleft(item)
                # _send marked the lease dead; loop re-evaluates

    def cancel(self, task_id: str, force: bool) -> bool | None:
        """None → not a direct task; bool → cancel outcome."""
        # still in the local queue: drop it before it ever leaves
        with self.lock:
            for key, q in self.local_queue.items():
                for item in q:
                    if item[0]["task_id"] == task_id:
                        q.remove(item)
                        self.core._direct_cancelled_local(item[0])
                        return True
        with self.lock:
            lease = next((l for ls in self.leases.values() for l in ls
                          if task_id in l.inflight), None)
        if lease is None:
            return None
        spec = lease.inflight.get(task_id)
        if spec is not None:
            spec["_cancelled"] = True
        rid = next(self._rid)
        from ray_tpu._private.worker import _Future

        fut = _Future()
        self._pending[rid] = fut
        try:
            lease.conn.send({"type": "cancel_direct", "rid": rid,
                             "task_id": task_id, "force": force})
            reply = fut.wait(30.0)
        except Exception:
            # force-kill closes the connection; the lease failure path marks
            # the task cancelled (spec["_cancelled"] above)
            return True if force else False
        finally:
            self._pending.pop(rid, None)
        if spec is not None and not reply.get("cancelled"):
            spec.pop("_cancelled", None)
        return bool(reply.get("cancelled"))

    # ------------------------------------------------------------ receive

    def _recv_loop(self, lease: _Lease):
        try:
            while True:
                msg = lease.conn.recv()
                rid = msg.get("rid")
                fut = self._pending.pop(rid, None) if rid is not None else None
                if fut is not None and "done" not in msg:
                    fut.set(msg)  # cancel reply
                    continue
                dones = msg.get("dones")
                if dones is None:
                    done = msg.get("done")
                    if done is None:
                        continue
                    dones = [(rid, done)]
                for _rid, done in dones:
                    tid = done["task_id"]
                    with lease.lock:
                        spec = lease.inflight.pop(tid, None)
                    if spec is not None:
                        self.core._on_direct_done(lease, spec, done)
                lease.last_used = lease.last_done = time.monotonic()
                self.pump(lease.key)
                with self.lock:
                    drained = lease.draining and not lease.inflight
                if drained:
                    self._return_lease(lease)
        except ConnectionClosed:
            self._fail_lease(lease)

    # ---------------------------------------------------------- lifecycle

    def _unlink(self, lease: _Lease) -> list[dict]:
        with self.lock:
            lease.dead = True
            self.by_wid.pop(lease.wid, None)
            ls = self.leases.get(lease.key)
            if ls and lease in ls:
                ls.remove(lease)
            with lease.lock:
                pending = list(lease.inflight.values())
                lease.inflight.clear()
        return pending

    def _fail_lease(self, lease: _Lease):
        if lease.dead:
            return
        pending = self._unlink(lease)
        try:
            lease.conn.close()
        except Exception:
            pass
        for spec in pending:
            self.core._direct_task_failed(spec, lease)
        self.pump(lease.key)

    def _return_lease(self, lease: _Lease):
        if lease.dead:
            return
        self._unlink(lease)
        try:
            lease.conn.send({"type": "bye"})
        except ConnectionClosed:
            pass
        try:
            lease.conn.close()
        except Exception:
            pass
        try:
            self.core.send_no_reply({"type": "return_lease",
                                     "tokens": {lease.wid: lease.token}})
        except Exception:
            pass

    def revoke(self, wid: str):
        """GCS wants this worker back (pending demand it can serve)."""
        lease = self.by_wid.get(wid)
        if lease is None:
            return
        with self.lock:
            lease.draining = True
            idle = not lease.inflight
        if idle:
            self._return_lease(lease)

    def reap_idle(self):
        """Periodic: pump backlogs, widen pools under them, return leases
        idle past LEASE_IDLE_S."""
        with self.lock:
            backlogged = [k for k, q in self.local_queue.items() if q]
        for key in backlogged:
            self.pump(key)
            self._grow(key, dict(key[0]), key[1], None)
        now = time.monotonic()
        with self.lock:
            busy_keys = {k for k, q in self.local_queue.items() if q}
            idle = [l for ls in self.leases.values() for l in ls
                    if not l.dead and not l.inflight and l.key not in busy_keys
                    and now - l.last_used > LEASE_IDLE_S]
        for lease in idle:
            self._return_lease(lease)

    def shutdown(self):
        with self.lock:
            all_leases = [l for ls in self.leases.values() for l in ls]
            queued = [item for q in self.local_queue.values() for item in q]
            self.local_queue.clear()
        for spec, _pin in queued:
            try:
                self.core._redirect_to_gcs(spec)
            except Exception:
                pass
        for lease in all_leases:
            self._return_lease(lease)
