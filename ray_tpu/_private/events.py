"""Structured cluster event log — producer-side buffering + shared filters.

Reference capability: the reference's export API / cluster event log
(python/ray/_private/event/, src/ray/gcs/gcs_server — node/actor/PG
lifecycle transitions recorded as typed events readable from the state
API) that makes "why is my actor pending" answerable from the control
store rather than from log spelunking (Ray, arXiv 1712.05889: the control
store is the debuggability backbone).

Design mirrors the task-event plane (task_events.py): each producer
process keeps a bounded ring of typed, severity-tagged event records;
drain() hands the not-yet-flushed suffix to the CoreWorker telemetry
flusher (drain-once, sequence-gated — same discipline as the flight
recorder), which ships batches to the GCS on the `cluster_events_report`
RPC. The GCS keeps its own ring (plus the sqlite `events` table for INFO+
so events survive a GCS restart) and answers `list_events` with the
server-side filtering implemented here.

Event-type / severity / field-name strings are wire protocol and live in
_private/constants.py; the `event-type-literal` graft_check forbids
re-spelled type literals at emit_event() call sites outside that module.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Optional

from . import constants as const
from .ray_config import RayConfig

_lock = threading.Lock()
_ring: Optional[collections.deque] = None
_seq = 0
_flushed_seq = 0
_enabled: Optional[bool] = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = RayConfig.instance().cluster_events
    return _enabled


def _buf() -> collections.deque:
    global _ring
    if _ring is None:
        _ring = collections.deque(maxlen=max(
            1, RayConfig.instance().cluster_events_ring_size))
    return _ring


def make_event(etype: str, *, severity: str = const.EVENT_SEVERITY_INFO,
               node: str = "", message: str = "", source: str = "",
               **fields) -> dict:
    """Build one event envelope (no buffering). GCS-side emission uses this
    directly so its ring and the producer rings share one record shape."""
    rec = {
        const.EVENT_FIELD_TYPE: etype,
        const.EVENT_FIELD_SEVERITY: severity,
        const.EVENT_FIELD_TS: time.time(),
        const.EVENT_FIELD_SOURCE: source or f"pid:{os.getpid()}",
        const.EVENT_FIELD_NODE: node,
        const.EVENT_FIELD_MESSAGE: message,
    }
    if fields:
        rec.update(fields)
    return rec


def emit_event(etype: str, *, severity: str = const.EVENT_SEVERITY_INFO,
               node: str = "", message: str = "", **fields) -> None:
    """Record one cluster event into this process's ring (controller-side
    producers: serve controller, train controller). The event type must be
    a constants.py EVENT_* name — literals here fail the
    event-type-literal static check."""
    global _seq
    if not enabled():
        return
    rec = make_event(etype, severity=severity, node=node, message=message,
                     **fields)
    with _lock:
        _seq += 1
        rec[const.EVENT_FIELD_SEQ] = _seq
        _buf().append(rec)


def drain() -> list:
    """Events recorded since the last drain that are STILL in the ring
    (drain-once; older entries rotated out carry the last-N semantics).
    Called by the CoreWorker telemetry flusher."""
    global _flushed_seq
    with _lock:
        out = [dict(r) for r in (_ring or ())
               if r[const.EVENT_FIELD_SEQ] > _flushed_seq]
        if out:
            _flushed_seq = out[-1][const.EVENT_FIELD_SEQ]
    return out


def recent() -> list:
    """The ring's current contents, oldest first (local inspection/tests)."""
    with _lock:
        return [dict(r) for r in (_ring or ())]


def reset() -> None:
    """Test helper: drop the ring + cached enable flag so a new RayConfig
    takes effect."""
    global _ring, _seq, _flushed_seq, _enabled
    with _lock:
        _ring = None
        _seq = 0
        _flushed_seq = 0
        _enabled = None


def severity_rank(severity: str) -> int:
    """Orderable severity (unknown strings sort highest so they are never
    filtered out by a min-severity bound)."""
    try:
        return const.EVENT_SEVERITIES.index(severity)
    except ValueError:
        return len(const.EVENT_SEVERITIES)


def filter_events(rows: list, *, min_severity: str = "", etype: str = "",
                  node: str = "", after_seq: int = 0, limit: int = 0) -> list:
    """Server-side event filtering shared by the GCS `list_events` handler
    and local consumers: min-severity bound, exact type / node match,
    seq watermark (drives `ray_tpu events --follow` polling), newest-N
    limit (applied LAST so `--limit` means "the newest N that match")."""
    out = rows
    if after_seq:
        out = [r for r in out if r.get(const.EVENT_FIELD_SEQ, 0) > after_seq]
    if min_severity:
        floor = severity_rank(min_severity)
        out = [r for r in out
               if severity_rank(r.get(const.EVENT_FIELD_SEVERITY, "")) >= floor]
    if etype:
        out = [r for r in out if r.get(const.EVENT_FIELD_TYPE) == etype]
    if node:
        out = [r for r in out if r.get(const.EVENT_FIELD_NODE) == node]
    if limit and limit > 0:
        out = out[-limit:]
    return [dict(r) for r in out]
