"""Fixed-point resource quantities.

(reference: src/ray/common/scheduling/fixed_point.h — resource amounts are
int64 multiples of 1e-4 so that repeated acquire/release cycles are exact;
float dicts with epsilon compares drift and eventually mis-schedule.)

The GCS stores node/bundle `total`/`available` dicts in these integer
units internally and converts at its API surfaces. Request-side resource
dicts (task/actor/PG specs, lease messages) stay user-facing floats and
are quantized at the scheduling chokepoints via `fp_dict` — `to_fp` is
deterministic per value, so an acquire followed by a release cancels to
exactly zero.
"""

from __future__ import annotations

PRECISION = 10_000  # 1e-4 resource units, matching the reference


def to_fp(v: float) -> int:
    return round(float(v) * PRECISION)


def from_fp(u: int) -> float:
    return u / PRECISION


def fp_dict(res: dict) -> dict:
    """Quantize a float resource dict into integer units."""
    return {k: to_fp(v) for k, v in res.items()}


def float_dict(res: dict) -> dict:
    """Integer units back to user-facing floats."""
    return {k: from_fp(v) for k, v in res.items()}
