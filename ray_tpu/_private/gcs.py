"""GCS — the control plane: object directory, scheduler, actor manager, KV,
virtual nodes, placement groups.

One process-wide server thread accepting unix-socket connections from the
driver and worker processes. Collapses the reference's head-node GcsServer +
per-node raylet NodeManager into one component, keeping the same
responsibilities and state machines:

- object directory + waiters      (reference: src/ray/gcs/gcs_server.h pubsub,
                                   object_manager/ownership_object_directory.h)
- lease-style task scheduling     (reference: raylet/scheduling/cluster_lease_manager.h:41
                                   + local_lease_manager.h:60 — tasks are queued until
                                   deps are local and resources free, then dispatched)
- actor lifecycle + restarts      (reference: gcs/gcs_actor_manager.h:93)
- named actors, internal KV       (reference: gcs/gcs_kv_manager.h:34)
- worker pool scale-up            (reference: raylet/worker_pool.h:280)
- virtual nodes                   (reference: one raylet per node; here nodes are
                                   resource partitions of the host — the same
                                   mechanism the reference's cluster_utils.Cluster
                                   test harness relies on, python/ray/cluster_utils.py:135)
- placement groups                (reference: gcs/gcs_placement_group_manager.h:50)
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import queue as _queue
import threading
import time
from typing import Callable

from ray_tpu._private import accelerators, constants as _const, fixed_point as fp, pg_policy
from ray_tpu._private.protocol import ConnectionClosed, MsgConnection, listen_unix
from ray_tpu._private.ray_config import RayConfig

logger = logging.getLogger(__name__)

INLINE_LIMIT = RayConfig.get("inline_object_limit")  # results below this live in the GCS table

DEFAULT_NODE = "node-0"
HEAD_HOST = "host-0"
# node-drain records persist in the kv table under this prefix; a restarted
# GCS (or a node re-registering) re-applies them — a drain survives both
_DRAIN_KV_PREFIX = "__node_drain::"
MAX_RECONSTRUCTIONS = 3
MAX_LINEAGE = RayConfig.get("max_lineage")
# chip spawns can block minutes in TPU plugin init; plain spawns are fast
SPAWN_TIMEOUT_S = 60.0
CHIP_SPAWN_TIMEOUT_S = 300.0
# pip envs build a venv + install inside the worker boot (each phase gets
# up to PIP_TIMEOUT_S=600s): the presumed-failed budget must exceed that
PIP_SPAWN_TIMEOUT_S = 1500.0


class _Worker:
    __slots__ = ("wid", "conn", "pid", "idle", "actor_id", "dead", "kind",
                 "running_tasks", "node_id", "tpu_chips", "host_id",
                 "ref_balance", "renv_hash", "direct_addr", "leased_to",
                 "lease_spec", "lease_token", "oom_why", "oom_ts",
                 "language", "functions")

    def __init__(self, wid: str, conn: MsgConnection, pid: int, kind: str, node_id: str,
                 tpu_chips: tuple = (), host_id: str = "host-0",
                 renv_hash: str = "", direct_addr: str | None = None,
                 language: str = "py", functions: tuple = ()):
        self.host_id = host_id
        self.wid = wid
        self.conn = conn
        self.pid = pid
        self.kind = kind  # "worker" | "driver"
        self.node_id = node_id
        self.idle = kind == "worker"
        self.running_tasks: dict[str, dict] = {}  # task_id → spec (GCS-side)
        self.actor_id: str | None = None
        self.dead = False
        # chips bound to this process at spawn via TPU_VISIBLE_CHIPS; fixed
        # for the process lifetime (jax backend init reads env once)
        self.tpu_chips = tuple(tpu_chips)
        # net process-level ref contributions, so a SIGKILLed process's
        # outstanding +1s can be reclaimed (reference: reference_counter
        # borrower death handling)
        self.ref_balance: dict[str, int] = {}
        # runtime-env fingerprint baked into the process at spawn
        # (reference: worker pool keyed by runtime-env hash)
        self.renv_hash = renv_hash
        # direct-dispatch plane (reference: leased-worker submission)
        self.direct_addr = direct_addr  # where leased callers connect
        self.leased_to: str | None = None  # caller wid holding the lease
        self.lease_spec: dict | None = None  # resources held by the lease
        self.lease_token: int | None = None  # guards stale release messages
        self.oom_why: str | None = None  # set by the memory monitor pre-kill
        self.oom_ts: float = 0.0  # when; stale tags are ignored on death
        # cross-language workers (reference: C++/Java API workers) execute
        # REGISTERED named functions; only specs of their language dispatch
        # to them
        self.language = language
        self.functions = tuple(functions)


class _Actor:
    __slots__ = (
        "aid", "state", "worker", "queue", "in_flight", "max_concurrency",
        "create_spec", "name",
        "restarts_left", "waiters", "kill_requested", "num_restarts",
        "max_task_retries",
        "groups", "method_groups", "group_in_flight", "group_queued",
    )

    def __init__(self, aid: str, create_spec: dict):
        self.aid = aid
        self.state = "pending"  # pending → alive → (restarting → alive)* → dead
        self.worker: str | None = None
        self.queue: collections.deque[dict] = collections.deque()
        self.in_flight = 0  # dispatched, not yet done (≤ max_concurrency)
        self.max_concurrency = int(create_spec.get("max_concurrency") or 1)
        # concurrency groups dispatch through their own lane (reference:
        # concurrency_group_manager.h — per-group limits): group methods are
        # never stuck behind a saturated default FIFO (e.g. serve health
        # probes vs a full data queue). max_concurrency above is the TOTAL
        # (default pool + group limits, summed at create_actor).
        self.groups: dict[str, int] = {
            str(k): max(1, int(v))
            for k, v in (create_spec.get("concurrency_groups") or {}).items()}
        self.method_groups: dict[str, str] = {
            str(k): str(v) for k, v in
            (create_spec.get("concurrency_group_methods") or {}).items()
            if str(v) in self.groups}
        self.group_in_flight: dict[str, int] = {}
        self.group_queued = 0  # queued specs bound for ANY group lane
        self.create_spec = create_spec
        self.name: str | None = create_spec.get("name")
        self.restarts_left: int = create_spec.get("max_restarts", 0)
        # in-flight method calls lost to a worker death are retried on the
        # restarted actor up to this many times each (-1 = unlimited);
        # 0 = fail with ActorDiedError (reference: actor max_task_retries)
        self.max_task_retries: int = int(
            create_spec.get("max_task_retries") or 0)
        self.num_restarts = 0
        self.waiters: list[tuple[MsgConnection, int]] = []  # ready-waiters
        self.kill_requested = False


class _VNode:
    """A virtual node: a resource partition with labels.

    (reference: one raylet per machine registered in gcs_node_manager.h:47;
    the in-process multi-node harness is how the reference tests multi-node,
    SURVEY.md §4.2.)"""

    __slots__ = ("node_id", "total", "available", "labels", "alive",
                 "chip_pool", "quarantined_chips", "draining", "drain_reason",
                 "drain_since", "drain_grace")

    def __init__(self, node_id: str, resources: dict, labels: dict | None = None):
        self.node_id = node_id
        # fixed-point integer units internally (fixed_point.py): exact
        # acquire/release round-trips, no epsilon compares
        self.total = fp.fp_dict(resources)
        self.available = dict(self.total)
        self.labels = dict(labels or {})
        self.alive = True
        # DRAINING: alive (running work continues + releases normally) but
        # excluded from every placement decision; one-way until node death
        # (reference: the reference GCS's DrainNode state, SURVEY §3.4)
        self.draining = False
        self.drain_reason = ""
        self.drain_since: float | None = None
        self.drain_grace: float | None = None
        # unbound TPU chip ids; chips leave the pool when a worker is spawned
        # with them visible and return when that worker dies (reference:
        # TPU_VISIBLE_CHIPS isolation, _private/accelerators/tpu.py:36)
        self.chip_pool: list[int] = list(
            range(int(fp.from_fp(self.total.get("TPU", 0)))))
        # chips held by a worker that was SIGKILLed mid-grant (OOM defense):
        # the shared device pool may be wedged, so they are withheld from
        # re-allocation until an operator re-enables them
        self.quarantined_chips: list[int] = []


class _PendingShards:
    """Pending plain-task queue sharded by (resource shape, renv_hash).

    Deep queues are the reference's scalability envelope (1M queued tasks on
    a node, release/benchmarks/README.md:29): per-event scheduler work must
    not scan the whole queue. Specs in one shard are uniform in everything
    placement-relevant except deps, so ONE feasibility probe (is there an
    idle worker of this shape / could one be spawned?) covers the entire
    shard — feasibility becomes a dict walk over shards instead of a spec
    scan. Specs with a scheduling strategy (PG / node affinity / labels)
    differ per-spec and live in the `misc` shard, scanned the old way.
    """

    __slots__ = ("shards", "misc", "ids")

    def __init__(self, specs=()):
        self.shards: dict[tuple, collections.deque] = {}
        self.misc: collections.deque = collections.deque()
        # task_id multiset for O(1) "is this tid queued?" probes (lineage
        # eviction asks per submit; a set build would be O(queue))
        self.ids: collections.Counter = collections.Counter()
        for s in specs:
            self.append(s)

    @staticmethod
    def key_of(spec: dict):
        if spec.get("strategy"):
            return None
        res = spec.get("resources") or {}
        return (tuple(sorted((k, float(v)) for k, v in res.items())),
                spec.get("renv_hash", ""), spec.get("lang", "py"))

    def _dq(self, spec: dict) -> collections.deque:
        k = self.key_of(spec)
        if k is None:
            return self.misc
        dq = self.shards.get(k)
        if dq is None:
            dq = self.shards[k] = collections.deque()
        return dq

    def append(self, spec: dict) -> None:
        self._dq(spec).append(spec)
        self.ids[spec["task_id"]] += 1

    def appendleft(self, spec: dict) -> None:
        self._dq(spec).appendleft(spec)
        self.ids[spec["task_id"]] += 1

    def note_consumed(self, tid: str) -> None:
        """A spec left the queue by direct deque manipulation (dispatch)."""
        n = self.ids.get(tid, 0) - 1
        if n <= 0:
            self.ids.pop(tid, None)
        else:
            self.ids[tid] = n

    def is_queued(self, tid: str) -> bool:
        return self.ids.get(tid, 0) > 0

    def __len__(self) -> int:
        return len(self.misc) + sum(len(d) for d in self.shards.values())

    def __bool__(self) -> bool:
        return bool(self.misc) or any(self.shards.values())

    def __iter__(self):
        yield from self.misc
        for dq in self.shards.values():
            yield from dq

    def remove_task_id(self, tid: str) -> list[dict]:
        """Remove (and return) every spec with this task id. O(total) —
        cancellation only."""
        removed: list[dict] = []

        def _filter(dq: collections.deque) -> collections.deque:
            kept: collections.deque = collections.deque()
            for s in dq:
                (removed if s["task_id"] == tid else kept).append(s)
            return kept

        self.misc = _filter(self.misc)
        for k in list(self.shards):
            self.shards[k] = _filter(self.shards[k])
            if not self.shards[k]:
                del self.shards[k]
        for _ in removed:
            self.note_consumed(tid)
        return removed


class _Bundle:
    __slots__ = ("total", "available", "node_id")

    def __init__(self, resources: dict):
        self.total = fp.fp_dict(resources)  # fixed-point units, like _VNode
        self.available = dict(self.total)
        self.node_id: str | None = None


class _PG:
    """Placement group state machine: pending → created → removed.

    (reference: gcs/gcs_placement_group_manager.h:50)"""

    __slots__ = ("pg_id", "bundles", "strategy", "name", "state", "waiters", "epoch")

    def __init__(self, pg_id: str, bundles: list[dict], strategy: str, name: str):
        self.pg_id = pg_id
        self.bundles = [_Bundle(b) for b in bundles]
        self.strategy = strategy
        self.name = name
        self.state = "pending"
        self.epoch = 0  # bumped on every (re)placement; stale releases detect it
        self.waiters: list[tuple[MsgConnection, int]] = []


def pg_ready_oid(pg_id: str) -> str:
    return f"{pg_id}r0000"


class GcsServer:
    def __init__(
        self,
        socket_path: str,
        total_resources: dict[str, float],
        spawn_worker_cb: Callable[[int, str, list], None],
        max_workers: int = 32,
        node_labels: dict | None = None,
        session_id: str = "",
        storage_path: str | None = None,
    ):
        self.socket_path = socket_path
        self.session_id = session_id
        self.lock = threading.RLock()
        self.spawn_worker_cb = spawn_worker_cb
        self.max_workers = max_workers
        # read once: _schedule is a hot path and the floor can't change
        # after server start
        self.warm_pool_size = int(RayConfig.get("warm_pool_size"))

        self.nodes: dict[str, _VNode] = {
            DEFAULT_NODE: _VNode(DEFAULT_NODE, total_resources, node_labels)
        }
        self.local_node_id = DEFAULT_NODE
        # cross-host state (reference: gcs_node_manager.h:47 node registry +
        # ownership_object_directory.h locations). "host-0" is the head.
        self.hosts: dict[str, dict] = {HEAD_HOST: {"object_addr": None, "conn": None}}
        self.node_hosts: dict[str, str] = {}  # node_id → host_id (default head)

        self.objects: dict[str, dict] = {}
        self.object_waiters: dict[str, list[tuple[MsgConnection, int]]] = {}
        # wid → oids it promised to publish (will_publish); consulted on its
        # death so the scan is O(its promises), entries dropped with the wid
        self._pub_promises: dict[str, set] = {}
        self._fn_access: dict[str, float] = {}  # fn: key → last touch ts
        self._pinned_fn_cache: tuple[float, set] | None = None
        self.workers: dict[str, _Worker] = {}
        self.pending_tasks = _PendingShards()
        self.pending_actor_creations: collections.deque[dict] = collections.deque()
        self.actors: dict[str, _Actor] = {}
        # (namespace, name) → actor id: named actors are scoped per
        # namespace (reference: ray namespaces — jobs in different
        # namespaces can reuse names without colliding)
        self.named_actors: dict[tuple, str] = {}
        self.pgs: dict[str, _PG] = {}
        self.named_pgs: dict[str, str] = {}
        self.pending_pgs: collections.deque[str] = collections.deque()
        self.kv: dict[str, bytes] = {}
        # retained specs of stateless tasks, for lineage reconstruction of
        # their outputs (reference: TaskManager lineage pinning)
        self.lineage: dict[str, dict] = {}
        # live streaming-generator tasks: task_id → stream state
        # (reference: streaming generators, _raylet.pyx:299)
        self.streams: dict[str, dict] = {}
        # per-host live tmpfs bytes; over RAY_TPU_OBJECT_STORE_CAPACITY the
        # LRU objects are spilled to disk (reference: local_object_manager.h:43)
        self.host_shm_bytes: collections.Counter = collections.Counter()
        self.spill_capacity = RayConfig.get("object_store_capacity")
        self._spawn_pending: dict[str, collections.deque] = collections.defaultdict(collections.deque)
        # normalized runtime envs by hash, for spawning matching workers
        self.runtime_envs: dict[str, dict] = {}
        self.stopped = False
        self._conn_threads: list[threading.Thread] = []
        self._listener = None
        self._accept_thread: threading.Thread | None = None
        # fault tolerance: optional write-through table persistence so a
        # restarted GCS rebuilds its managers from storage (reference: Redis
        # store client + gcs_init_data rebuild, redis_store_client.h:126)
        self.storage = None
        sp = storage_path if storage_path is not None else RayConfig.get("gcs_storage_path")
        if sp:
            from ray_tpu._private.gcs_storage import GcsStorage

            self.storage = GcsStorage(sp)
        # metrics / introspection
        self.task_counter = collections.Counter()
        self.task_events: collections.deque = collections.deque(maxlen=10000)
        # cluster-wide user/system metrics, keyed by metric name; per-source
        # series so restarts/re-reports replace instead of double-count
        # (reference: metrics agent aggregation, _private/metrics_agent.py:628)
        self.metrics: dict[str, dict] = {}
        # compiled-DAG registry: dag_id → metadata registered at
        # experimental_compile (nodes, actors, channel topology,
        # fallback_reason), dropped at teardown or driver death. Session-
        # scoped like task_events — a DAG cannot outlive its driver, so the
        # table is in-memory only.
        self.compiled_dags: dict[str, dict] = {}
        # serve flight-recorder log: last-N request summaries shipped by
        # worker flushers (request_log_report), read by `ray_tpu trace list`
        # and the dashboard's /api/requests
        self.request_log: collections.deque = collections.deque(maxlen=1024)
        # structured cluster event log (_private/events.py): node/actor/PG/
        # lease lifecycle transitions, emitted here at their source and
        # ingested from controller processes via cluster_events_report.
        # INFO+ events write through to the sqlite `events` table so the
        # log survives a GCS restart; the ring answers list_events.
        self._events_enabled = bool(RayConfig.get("cluster_events"))
        self._events_ring_size = max(
            1, int(RayConfig.get("cluster_events_ring_size")))
        self.cluster_events: collections.deque = collections.deque(
            maxlen=self._events_ring_size)
        self._cluster_event_seq = 0
        self._events_lock = threading.Lock()
        # scheduler decision traces: actor_id/pg_id → attribution record
        # (enqueue time, attempts, queue wait, chosen node, lease RTT) kept
        # while the entity exists so sched_explain can answer "why is X
        # pending" / "where and how fast did X place"
        self.sched_traces: dict[str, dict] = {}
        # server-side RPC latency per request type — the measurement floor
        # for control-plane scale work. UNREGISTERED histogram: the GCS
        # often shares a process with the driver, whose flusher would
        # otherwise ship the same series a second time; instead the series
        # folds into metrics_snapshot under the reserved "gcs" source.
        from ray_tpu.util.metrics import Histogram

        self._rpc_hist = Histogram(
            "ray_tpu_gcs_rpc_seconds",
            "server-side GCS RPC handler latency per request type "
            "(includes any handler-side blocking)",
            boundaries=[0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                        0.1, 0.5, 1.0, 5.0],
            tag_keys=("rpc",), register=False)
        self._rpc_bound: dict[str, object] = {}
        self._rpc_other = self._rpc_hist.bind({"rpc": "other"})
        self._rpc_bound_lock = threading.Lock()
        # nodes currently DRAINING — same unregistered pattern as _rpc_hist
        # (the value is computed from self.nodes at snapshot time; the Gauge
        # object exists so the metric is declared head-side, not shipped
        # twice by a co-resident driver flusher)
        from ray_tpu.util.metrics import Gauge

        self._draining_gauge = Gauge(
            "ray_tpu_nodes_draining",
            "nodes in DRAINING state: no new placements; resident train "
            "workers grace-checkpoint before the node is terminated",
            register=False)
        # scheduler decision metrics — same unregistered fold-in pattern.
        # The histogram observes queue-wait at dispatch/placement time and
        # creation round-trips at completion; the counter is the
        # decisions/s floor the 1000-node scale harness measures against.
        from ray_tpu.util.metrics import Counter

        self._sched_hist = Histogram(
            "ray_tpu_sched_decision_seconds",
            "scheduler decision latency: queue-wait until dispatch/placement "
            "(outcome=dispatched/placed) and actor-creation lease RTT "
            "(outcome=created)",
            boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                        5.0, 30.0, 120.0],
            tag_keys=("kind", "outcome"), register=False)
        self._sched_counter = Counter(
            "ray_tpu_sched_decisions_total",
            "terminal scheduler decisions by work kind and outcome",
            tag_keys=("kind", "outcome"), register=False)
        self._sched_pending_gauge = Gauge(
            "ray_tpu_sched_pending",
            "work items waiting on a placement decision, by kind",
            tag_keys=("kind",), register=False)
        # retained metric TIME SERIES, head-side (reference: the dashboard's
        # metrics stack — per-node agents scraped into Prometheus,
        # dashboard/modules/metrics/metrics_head.py; here the GCS keeps a
        # bounded in-memory window so the UI graphs history with no
        # external TSDB): per-node samples appended on each resource_view
        # delta, cluster samples on each health-loop tick
        self.node_history: dict[str, collections.deque] = {}
        self.cluster_history: collections.deque = collections.deque(
            maxlen=720)
        # general long-poll pubsub: channel → list of (conn, rid) pollers and
        # buffered per-subscriber queues (reference: src/ray/pubsub/publisher.h:159)
        self.pubsub_queues: dict[tuple[str, str], collections.deque] = {}
        self.pubsub_pollers: dict[tuple[str, str], tuple[MsgConnection, int]] = {}
        self.pubsub_conns: dict[tuple[str, str], MsgConnection] = {}
        # in-flight RDT exports: token → (requester conn, rid)
        self._tensor_exports: dict[str, tuple] = {}
        # direct-dispatch leases (reference: cluster_lease_manager.h:41):
        # grant tokens guard against stale release messages; the holder index
        # lets caller death release everything it held
        self._lease_seq = 0
        self._leases_by_holder: dict[str, set[str]] = {}
        # attached autoscalers can GROW the cluster: infeasible-now
        # placement groups then stay pending instead of failing fast
        # (reference: infeasibility is judged against the autoscaler's max
        # cluster shape, which only the autoscaler knows). Tracked per
        # connection so autoscaler death restores fail-fast.
        self._autoscaler_conns: set = set()
        # autoscaler instance state machine (reference: v2 instance_manager's
        # InstanceStorage lives in the GCS so a restarted reconciler rebuilds
        # from the table): instance_id → record dict, write-through to the
        # sqlite `instances` table when persistence is on
        self.autoscaler_instances: dict[str, dict] = {}
        # serve control-plane state (reference: the Serve controller
        # checkpoints ApplicationState/DeploymentState into the GCS,
        # serve/_private/controller.py:102): key → record dict, write-through
        # to the sqlite `serve` table. A crash-restarted ServeController
        # rebuilds deployments/replicas/routes from here and re-adopts live
        # replica actors instead of restarting them.
        self.serve_table: dict[str, dict] = {}
        # caller-reported local submission backlogs, piggybacked on lease
        # requests (reference: backlog_size in lease requests feeds the
        # autoscaler's demand view)
        self._direct_backlog: dict[tuple, tuple] = {}  # (caller,key)→(res,n,ts)
        # publish() is called from paths holding self.lock — a slow
        # subscriber socket must not stall the control plane, so replies to
        # parked pollers go through this queue to a dedicated sender thread
        self._pub_sendq: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._pub_thread: threading.Thread | None = None

    # aggregate views (cluster_state compatibility; floats at the surface)
    @property
    def total(self) -> dict:
        out: dict[str, int] = {}
        for n in self.nodes.values():
            if n.alive:
                for k, v in n.total.items():
                    out[k] = out.get(k, 0) + v
        return fp.float_dict(out)

    @property
    def available(self) -> dict:
        # draining nodes are excluded: their capacity is unschedulable, so
        # counting it would make elastic restarts size attempts against
        # nodes that are about to terminate (and would hide the unmet
        # demand the autoscaler should replace)
        out: dict[str, int] = {}
        for n in self.nodes.values():
            if n.alive and not n.draining:
                for k, v in n.available.items():
                    out[k] = out.get(k, 0) + v
        return fp.float_dict(out)

    # ------------------------------------------------------------------ server

    def _restore_from_storage(self):
        """Rebuild manager state from persisted tables (reference:
        gcs_init_data.h — GCS restart rebuild in Redis mode)."""
        if self.storage is None:
            return
        with self.lock:
            for k, v in self.storage.items("kv"):
                self.kv[k] = v
            for k, v in self.storage.items("instances"):
                self.autoscaler_instances[k] = v
            for k, v in self.storage.items("serve"):
                self.serve_table[k] = v
        self._restore_events_from_storage()
        for _, spec in self.storage.items("pgs"):
            self._create_pg(dict(spec), _persist=False)
        for _, spec in self.storage.items("actors"):
            # actors restart from their creation spec on the rebuilt cluster
            # (fresh state, same identity/name — reference restarts actors
            # whose processes died with the old GCS's nodes)
            self._create_actor(dict(spec), _persist=False)

    def _health_loop(self):
        """Actively ping follower-host agents; hosts missing too many pongs
        are declared dead (reference: gcs_health_check_manager.h:45, config
        thresholds in ray_config_def.h:877). Same-host worker death is
        already observed through connection close."""
        period = RayConfig.get("health_check_period_s")
        thresh = RayConfig.get("health_check_failure_threshold")
        while not self.stopped:
            time.sleep(period)
            now = time.monotonic()
            self._sample_histories()
            # expire parked relay waiters (stack dumps / tensor exports) so
            # a worker wedged in native code can't hang the requester forever
            with self.lock:
                expired = [(tok, w) for tok, w in self._tensor_exports.items()
                           if now - w[3] > (w[4] if len(w) > 4 else 30.0)]
                for tok, _ in expired:
                    self._tensor_exports.pop(tok, None)
            for _, (wconn, wrid, *_rest) in expired:
                try:
                    wconn.send({"rid": wrid, "ok": False,
                                "error": "target did not answer within 30s "
                                         "(wedged in native code?)"})
                except ConnectionClosed:
                    pass
            dead_hosts = []
            with self.lock:
                targets = [(hid, info) for hid, info in self.hosts.items()
                           if hid != HEAD_HOST and info.get("conn") is not None]
                for hid, info in targets:
                    last = info.get("last_pong")
                    if last is None:
                        info["last_pong"] = now  # first check cycle
                    elif now - last > period * thresh:
                        dead_hosts.append(hid)
            for hid in dead_hosts:
                logger.warning("host %s failed health checks; removing", hid)
                self._remove_host(hid)
            for hid, info in targets:
                if hid in dead_hosts:
                    continue
                try:
                    info["conn"].send({"type": "ping"})
                except (ConnectionClosed, Exception):
                    self._remove_host(hid)

    def _sample_histories(self):
        """One retained-history tick: cluster-level gauges plus the head
        host's own resource view (followers report theirs via ray_syncer
        deltas; without this the head node would have no series at all)."""
        from ray_tpu._private.memory_monitor import host_memory_usage

        try:
            load1 = os.getloadavg()[0]
        except OSError:
            load1 = 0.0
        try:
            mem = host_memory_usage()
        except Exception:
            mem = 0.0
        ts = time.time()
        with self.lock:
            live_workers = 0
            head_workers = 0
            for w in self.workers.values():
                if w.kind == "worker" and not w.dead:
                    live_workers += 1
                    if w.host_id == HEAD_HOST:
                        head_workers += 1
            self.cluster_history.append({
                "ts": ts,
                "pending_tasks": len(self.pending_tasks),
                "live_actors": sum(1 for a in self.actors.values()
                                   if a.state == "alive"),
                "live_workers": live_workers,
                "placement_groups": len(self.pgs),
                "objects": len(self.objects),
            })
            hist = self.node_history.setdefault(
                HEAD_HOST, collections.deque(maxlen=720))
            # the head's PER-NODE series counts head-local workers only —
            # followers report their own via resource_view deltas
            hist.append({"ts": ts, "mem_usage": round(mem, 4),
                         "load1": round(load1, 2),
                         "num_worker_procs": head_workers})

    def start(self):
        self._restore_from_storage()
        for node_id in list(self.nodes):
            self._emit_event(_const.EVENT_NODE_JOIN, node=node_id,
                             message="head-local virtual node online")
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="gcs-health")
        self._health_thread.start()
        self._pub_thread = threading.Thread(
            target=self._pub_send_loop, daemon=True, name="gcs-pubsub")
        self._pub_thread.start()
        self._listener = listen_unix(self.socket_path)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._listener,), daemon=True,
            name="gcs-accept")
        self._accept_thread.start()
        # always also listen on TCP so follower hosts / remote drivers can
        # join (reference capability: gRPC control plane, rpc/grpc_server.h).
        # Loopback by default — the protocol executes pickled code, so only
        # bind externally (RAY_TPU_BIND_HOST=0.0.0.0) on trusted networks.
        import os as _os

        from ray_tpu._private.protocol import listen_tcp

        self._tcp_listener = listen_tcp(RayConfig.get("bind_host"), 0)
        self.tcp_port = self._tcp_listener.getsockname()[1]
        self._tcp_accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._tcp_listener,), daemon=True,
            name="gcs-accept-tcp")
        self._tcp_accept_thread.start()
        # OOM defense for the head host (reference: memory_monitor.h:52 +
        # worker_killing_policy_group_by_owner.h:87); node agents run their
        # own for follower hosts
        self._mem_monitor = None
        refresh_ms = RayConfig.get("memory_monitor_refresh_ms")
        if refresh_ms > 0:
            from ray_tpu._private.memory_monitor import MemoryMonitor

            self._mem_monitor = MemoryMonitor(
                threshold=RayConfig.get("memory_usage_threshold"),
                period_s=refresh_ms / 1000.0,
                pick_victim=self._pick_oom_victim,
                on_kill=self._note_oom_kill).start()

    @staticmethod
    def _oom_fresh(w) -> bool:
        """A pre-kill OOM tag explains a death only while fresh — a pick
        whose reply was lost (agent never killed) must not blame a much
        later unrelated death on memory pressure."""
        return (w is not None and w.oom_why is not None
                and time.monotonic() - w.oom_ts < 30.0)

    def _pick_oom_victim(self, host_id: str = HEAD_HOST):
        """Newest retriable running plain task's worker on `host_id`, then
        any running plain task's worker, then the newest-leased direct
        worker — never actors or infrastructure (reference:
        worker_killing_policy_group_by_owner.h:87). Node agents delegate
        their victim choice here too (pick_oom_victim RPC): only the GCS
        knows which pids run retriable tasks vs actors."""
        # killing a worker mid-TPU-grant can wedge the host's shared device
        # pool (backend init hangs for every later process), so chip-holding
        # workers are excluded unless explicitly opted in — and even then
        # ranked strictly after every chip-free candidate
        allow_tpu = RayConfig.get("oom_kill_tpu_workers")
        with self.lock:
            best = None  # ((chip_free, retriable, newest_ts), worker)
            for w in self.workers.values():
                if (w.kind != "worker" or w.dead or w.host_id != host_id
                        or w.actor_id is not None or not w.pid):
                    continue
                if w.tpu_chips and not allow_tpu:
                    continue
                plain = [s for s in w.running_tasks.values()
                         if s.get("kind") == "task"]
                if not plain:
                    continue
                ts = max(s.get("_ts", 0.0) for s in plain)
                retriable = any(s.get("retries_used", 0) < s.get("max_retries", 0)
                                for s in plain)
                key = (0 if w.tpu_chips else 1, 1 if retriable else 0, ts)
                if best is None or key > best[0]:
                    best = (key, w)
            if best is not None:
                w = best[1]
                names = [s.get("name") or s.get("task_id", "")[:8]
                         for s in w.running_tasks.values()]
                return w.pid, f"worker {w.wid[:8]} running {names}"
            leased = [w for w in self.workers.values()
                      if w.kind == "worker" and not w.dead and w.pid
                      and w.host_id == host_id and w.leased_to is not None
                      and (allow_tpu or not w.tpu_chips)]
            if leased:
                w = max(leased,
                        key=lambda x: (0 if x.tpu_chips else 1,
                                       x.lease_token or 0))
                return w.pid, f"leased worker {w.wid[:8]}"
        return None

    def _note_oom_kill(self, pid: int, why: str | None,
                       host_id: str = HEAD_HOST) -> None:
        with self.lock:
            for w in self.workers.values():
                # pids are per-host namespaces: match host too, or a
                # follower worker sharing the pid gets mis-tagged
                if w.pid == pid and w.host_id == host_id and not w.dead:
                    w.oom_why = why
                    w.oom_ts = time.monotonic()
                    break
        if why is not None:
            self.publish("errors", {"kind": "oom_kill", "error": why,
                                    "ts": time.time()})

    def crash_for_testing(self):
        """Abruptly drop every connection and listener WITHOUT the graceful
        worker-exit handshake — simulates a GCS process crash for fault-
        tolerance tests (reference: GCS restart tests with external Redis,
        test_gcs_fault_tolerance.py)."""
        import socket as _socket

        self._pub_sendq.put(None)  # stop the pubsub sender thread
        with self.lock:
            self.stopped = True
            conns = [w.conn for w in self.workers.values() if not w.dead]
            conns += [h["conn"] for h in self.hosts.values() if h.get("conn")]
        if self.storage is not None:
            self.storage.close()
        for listener in (self._listener, getattr(self, "_tcp_listener", None)):
            if listener is not None:
                try:
                    listener.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
        for c in conns:
            try:
                c.close()
            except Exception:
                pass
        try:
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.settimeout(0.2)
            s.connect(self.socket_path)
            s.close()
        except OSError:
            pass
        if getattr(self, "tcp_port", None):
            try:  # wake the TCP accept thread so it closes its listener too
                s = _socket.create_connection(("127.0.0.1", self.tcp_port),
                                              timeout=0.2)
                s.close()
            except OSError:
                pass

    def stop(self):
        if getattr(self, "_mem_monitor", None) is not None:
            self._mem_monitor.stop()
        if self.storage is not None:
            self.storage.close()
        self._pub_sendq.put(None)
        with self.lock:
            self.stopped = True
            for w in self.workers.values():
                if w.kind == "worker" and not w.dead:
                    try:
                        w.conn.send({"type": "exit"})
                    except ConnectionClosed:
                        pass
        # Wake the accept threads WITHOUT closing the fds: close() here would
        # free the fd numbers while the accept threads may be entering
        # accept(2), and a new session's listener can reuse those numbers —
        # the stale thread then steals the new listener's connections and
        # serves them with this stopped GCS (observed: drivers registering
        # into a dead session and hanging). shutdown() unblocks accept but
        # keeps the fd allocated; the owning accept thread closes it.
        import socket as _socket  # local: protocol owns all other socket use

        for listener in (self._listener, getattr(self, "_tcp_listener", None)):
            if listener is not None:
                try:
                    listener.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
        # belt-and-braces: a no-op connect unblocks accept() even where
        # shutdown() on a listening socket doesn't
        try:
            s = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            s.settimeout(0.2)
            s.connect(self.socket_path)
            s.close()
        except OSError:
            pass
        if getattr(self, "tcp_port", None):
            try:
                s = _socket.create_connection(("127.0.0.1", self.tcp_port), timeout=0.2)
                s.close()
            except OSError:
                pass

    def _accept_loop(self, listener):
        while not self.stopped:
            try:
                sock, _ = listener.accept()
            except OSError:
                break
            if self.stopped:
                try:
                    sock.close()
                except OSError:
                    pass
                break
            conn = MsgConnection(sock)
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True, name="gcs-conn")
            t.start()
            self._conn_threads.append(t)
        try:
            listener.close()  # sole closer: no fd reuse while accept may run
        except OSError:
            pass

    # label-cardinality cap for the per-RPC-type histogram: the type string
    # is client-supplied, so without a bound a misbehaving/skewed client
    # could grow GCS memory and every snapshot with garbage series. The
    # real dispatch table is ~100 types; overflow buckets as "other".
    _RPC_TYPE_CAP = 160

    def _observe_rpc(self, rpc_type, seconds: float) -> None:
        """Per-type server-side latency. Bound labelsets are cached so the
        steady-state cost is one lock-free histogram observe per request;
        past the cap, unseen types share one uncached "other" bind so
        neither the series set nor the cache grows."""
        b = self._rpc_bound.get(rpc_type)
        if b is None:
            with self._rpc_bound_lock:
                b = self._rpc_bound.get(rpc_type)
                if b is None:
                    if len(self._rpc_bound) < self._RPC_TYPE_CAP:
                        b = self._rpc_bound[rpc_type] = self._rpc_hist.bind(
                            {"rpc": str(rpc_type)})
                    else:
                        b = self._rpc_other
        b.observe(seconds)

    def _serve_conn(self, conn: MsgConnection):
        wid = None
        try:
            while True:
                msg = conn.recv()
                _t0 = time.perf_counter()
                try:
                    wid = self._handle(conn, msg, wid)
                except ConnectionClosed:
                    raise
                except Exception:  # noqa: BLE001 — one bad request must not kill the conn thread
                    logger.exception("gcs: error handling %s", msg.get("type"))
                    if "rid" in msg:
                        try:
                            conn.send({"rid": msg["rid"], "ok": False,
                                       "error": "internal error; see GCS log"})
                        except ConnectionClosed:
                            raise
                finally:
                    self._observe_rpc(msg.get("type"),
                                      time.perf_counter() - _t0)
        except ConnectionClosed:
            if wid is not None:
                self._on_worker_death(wid)
            host_id = next((h for h, info in self.hosts.items()
                            if info.get("conn") is conn), None)
            if host_id is not None:
                self._remove_host(host_id)
            # drop pubsub subscriber state owned by this connection — a
            # crashed subscriber must not leave queues accumulating forever
            with self.lock:
                self._autoscaler_conns.discard(id(conn))
                dead_keys = [k for k, c in self.pubsub_conns.items() if c is conn]
                for k in dead_keys:
                    self.pubsub_conns.pop(k, None)
                    self.pubsub_queues.pop(k, None)
                    self.pubsub_pollers.pop(k, None)

    # --------------------------------------------------------------- dispatch

    def _handle(self, conn: MsgConnection, msg: dict, wid: str | None) -> str | None:
        t = msg["type"]
        if t == "register":
            if msg.get("codec") == "json":
                # language-neutral peer (e.g. the C++ worker): reply frames
                # must be JSON from the first message on
                conn.codec = "json"
            with self.lock:
                wid = msg["wid"]
                node_id = msg.get("node_id") or DEFAULT_NODE
                chips = tuple(msg.get("tpu_chips") or ())
                renv_hash = msg.get("renv_hash", "")
                accepted = True
                if msg["kind"] == "worker":
                    # retire the spawn-accounting entry for this worker,
                    # matching by chip assignment + runtime-env hash so a
                    # specialized spawn isn't credited to a plain registration
                    dq = self._spawn_pending[node_id]
                    for i, (_, c, rh) in enumerate(dq):
                        if tuple(c or ()) == chips and rh == renv_hash:
                            del dq[i]
                            break
                    else:
                        if chips:
                            # no pending entry: this chip spawn was presumed
                            # failed and its chips refunded. Accept only if
                            # the chips are still unbound — otherwise another
                            # worker holds them and admitting this one would
                            # double-bind the physical chips.
                            node = self.nodes.get(node_id)
                            pool = node.chip_pool if (node and node.alive) else []
                            if all(c in pool for c in chips):
                                for c in chips:
                                    pool.remove(c)
                            else:
                                accepted = False
                        elif dq:
                            dq.popleft()
                if accepted:
                    self.workers[wid] = _Worker(
                        wid, conn, msg.get("pid", 0), msg["kind"], node_id,
                        tpu_chips=chips, host_id=msg.get("host") or HEAD_HOST,
                        renv_hash=renv_hash,
                        direct_addr=msg.get("direct_addr"),
                        language=msg.get("language", "py"),
                        functions=tuple(msg.get("functions") or ()))
            if not accepted:
                conn.send({"rid": msg["rid"], "ok": False,
                           "error": "stale chip binding; exit"})
                try:
                    conn.send({"type": "exit"})
                except ConnectionClosed:
                    pass
                return None
            conn.send({"rid": msg["rid"], "ok": True})
            self._schedule()
            return wid
        if t == "get_session":
            conn.send({"rid": msg["rid"], "session_id": self.session_id})
            return wid
        if t == "register_host":
            with self.lock:
                host_id = msg["host_id"]
                node_id = msg.get("node_id") or host_id
                self.hosts[host_id] = {
                    "object_addr": msg.get("object_addr"), "conn": conn}
                self.node_hosts[node_id] = host_id
                self.nodes[node_id] = _VNode(
                    node_id, msg["resources"], msg.get("labels"))
                self._reapply_drain_locked(self.nodes[node_id])
            self._emit_event(_const.EVENT_NODE_JOIN, node=node_id,
                             message=f"host {host_id} registered",
                             host=host_id)
            conn.send({"rid": msg["rid"], "ok": True,
                       "session_id": self.session_id})
            self._schedule()
            return wid
        if t == "resource_view":
            # follower load delta (reference: ray_syncer resource-view
            # broadcasts) — stored on the host entry, served per node by
            # list_nodes (and the dashboard's nodes page on top of it)
            with self.lock:
                info = self.hosts.get(msg.get("host_id"))
                if info is not None:
                    info["view"] = {
                        "mem_usage": msg.get("mem_usage"),
                        "load1": msg.get("load1"),
                        "num_worker_procs": msg.get("num_worker_procs"),
                        "ts": time.monotonic(),
                    }
                    hist = self.node_history.setdefault(
                        msg.get("host_id"),
                        collections.deque(maxlen=720))
                    hist.append({"ts": time.time(),
                                 "mem_usage": msg.get("mem_usage"),
                                 "load1": msg.get("load1"),
                                 "num_worker_procs":
                                     msg.get("num_worker_procs")})
            return wid
        if t == "pong":
            with self.lock:
                info = self.hosts.get(msg.get("host_id"))
                if info is not None:
                    info["last_pong"] = time.monotonic()
            return wid
        if t == "log_line":
            # fan out to every driver (reference: log_monitor republishing
            # worker logs to drivers via GCS pubsub)
            with self.lock:
                drivers = [w.conn for w in self.workers.values()
                           if w.kind == "driver" and not w.dead]
            for dconn in drivers:
                try:
                    dconn.send({"type": "log_line", "source": msg["source"],
                                "line": msg["line"]})
                except ConnectionClosed:
                    pass
            return wid
        if t == "ref_delta":
            self._on_ref_delta(msg["deltas"], wid)
            return wid
        if t == "stream_item":
            with self.lock:
                st = self.streams.get(msg["task_id"])
            if st is None:
                # consumer released the stream: drop the orphan item's shm
                # copy and tell the producer to stop generating
                if msg.get("where") == "shm":
                    self._delete_host_copy(msg["oid"], msg.get("host") or HEAD_HOST)
                with self.lock:
                    prod = self.workers.get(msg.get("wid") or "")
                if prod is not None and not prod.dead:
                    try:
                        prod.conn.send({"type": "stream_cancel",
                                        "task_id": msg["task_id"]})
                    except ConnectionClosed:
                        pass
                return wid
            self._on_object_ready(
                msg["oid"], where=msg.get("where", "shm"),
                inline=msg.get("inline"), size=msg.get("size", 0),
                is_error=False, host=msg.get("host") or HEAD_HOST,
                contained=msg.get("contained"), tier=msg.get("tier", "shm"))
            with self.lock:
                st = self.streams.get(msg["task_id"])
                if st is not None:
                    st["producer"] = msg.get("wid") or st["producer"]
                    st["items"].append(msg["oid"])
                    waiters, st["waiters"] = st["waiters"], []
                else:
                    waiters = []
            for wconn, rid, idx in waiters:
                self._answer_stream_next(wconn, rid, msg["task_id"], idx)
            return wid
        if t == "stream_end":
            with self.lock:
                st = self.streams.get(msg["task_id"])
                if st is not None:
                    st["done"] = True
                    st["error"] = msg.get("error")
                    st["producer"] = msg.get("wid") or st["producer"]
                    waiters, st["waiters"] = st["waiters"], []
                else:
                    waiters = []
            for wconn, rid, idx in waiters:
                self._answer_stream_next(wconn, rid, msg["task_id"], idx)
            return wid
        if t == "stream_next":
            self._answer_stream_next(conn, msg["rid"], msg["task_id"], msg["index"])
            return wid
        if t == "stream_consumed":
            with self.lock:
                st = self.streams.get(msg["task_id"])
                if st is None:
                    return wid
                st["consumed"] = max(st["consumed"], msg["index"])
                prod = self.workers.get(st["producer"]) if st["producer"] else None
            if prod is not None and not prod.dead:
                try:
                    prod.conn.send({"type": "stream_ack", "task_id": msg["task_id"],
                                    "consumed": msg["index"]})
                except ConnectionClosed:
                    pass
            return wid
        if t == "stream_release":
            # consumer dropped the generator: free whatever it didn't take
            with self.lock:
                st = self.streams.pop(msg["task_id"], None)
                leftover = st["items"][st["consumed"]:] if st else []
            if leftover:
                self._free_objects(leftover)
            return wid
        if t == "object_lost":
            action = self._reconstruct_or_report(msg["oid"])
            conn.send({"rid": msg["rid"], "action": action})
            return wid
        if t == "submit_task":
            self._submit_task(msg["spec"])
            # submission is async (reference: .remote() never waits on the
            # GCS); callers send rid-less fire-and-forget submits with a
            # periodic synchronous one as backpressure
            if "rid" in msg:
                conn.send({"rid": msg["rid"], "ok": True})
        elif t == "task_done":
            if conn.codec == "json":
                self._convert_cross_lang_done(msg)
            self._on_task_done(msg)
        elif t == "object_put":
            self._on_object_ready(msg["oid"], where=msg.get("where", "shm"),
                                  inline=msg.get("inline"), size=msg.get("size", 0),
                                  is_error=msg.get("is_error", False),
                                  host=msg.get("host") or HEAD_HOST,
                                  pin=msg.get("pin", False),
                                  contained=msg.get("contained"),
                                  tier=msg.get("tier", "shm"))
        elif t == "objects_evicted":
            # arena evict-to-spill on some host: those copies left tmpfs
            # (still readable from that host's spill tier)
            self._on_objects_evicted(msg.get("host") or HEAD_HOST,
                                     msg.get("oids") or [])
        elif t == "lease_workers":
            self._lease_workers(conn, msg, wid)
        elif t == "return_lease":
            for lw, tok in (msg.get("tokens") or {}).items():
                self._release_lease(lw, tok)
        elif t == "lease_released":
            # a worker reporting its caller's connection closed
            self._release_lease(msg["wid"], msg.get("token"))
        elif t == "pick_oom_victim":
            # a node agent under memory pressure asks for a victim on ITS
            # host: the GCS applies the same policy it uses for the head
            # (never actors/infrastructure) and tags the reason pre-kill
            victim = self._pick_oom_victim(msg.get("host_id") or HEAD_HOST)
            pid = None
            if victim is not None:
                pid, desc = victim
                why = (f"{msg.get('why', 'host memory pressure')}; "
                       f"killed {desc}")
                self._note_oom_kill(pid, why,
                                    host_id=msg.get("host_id") or HEAD_HOST)
            conn.send({"rid": msg["rid"], "pid": pid})
        elif t == "autoscaler_attach":
            with self.lock:
                self._autoscaler_conns.add(id(conn))
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "instance_put":
            # autoscaler instance state machine write-through (reference: v2
            # instance_storage) — the reply IS the durability ack: the
            # reconciler orders provider side-effects after it, so persist
            # (memory + sqlite) strictly before sending
            rec = dict(msg["instance"])
            iid = str(rec["instance_id"])
            with self.lock:
                prev = self.autoscaler_instances.get(iid)
                self.autoscaler_instances[iid] = rec
            if self.storage is not None:
                self.storage.put("instances", iid, rec)
            old_state = (prev or {}).get("state")
            new_state = rec.get("state")
            if new_state != old_state:
                self._emit_event(
                    _const.EVENT_AUTOSCALER_INSTANCE,
                    node=str(rec.get("node_id") or ""),
                    message=f"instance {iid}: "
                            f"{old_state or 'NEW'} -> {new_state}",
                    instance_id=iid, from_state=old_state, to_state=new_state)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "instance_delete":
            iid = str(msg["instance_id"])
            with self.lock:
                self.autoscaler_instances.pop(iid, None)
            if self.storage is not None:
                self.storage.delete("instances", iid)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "instance_list":
            with self.lock:
                recs = [dict(r) for r in self.autoscaler_instances.values()]
            conn.send({"rid": msg["rid"], "instances": recs})
        elif t == "serve_put":
            # serve control-plane write-through (reference: serve controller
            # checkpoints before side effects) — same contract as
            # instance_put: the reply IS the durability ack, so persist
            # (memory + sqlite) strictly before sending it
            key = str(msg["key"])
            rec = dict(msg["record"])
            with self.lock:
                self.serve_table[key] = rec
            if self.storage is not None:
                self.storage.put("serve", key, rec)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "serve_delete":
            key = str(msg["key"])
            with self.lock:
                self.serve_table.pop(key, None)
            if self.storage is not None:
                self.storage.delete("serve", key)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "serve_list":
            with self.lock:
                if msg.get("keys_only"):
                    conn.send({"rid": msg["rid"],
                               "keys": list(self.serve_table)})
                    return wid
                # light = control state only: blob rows carry the pickled
                # callables and must not ship to list-only consumers (the
                # dashboard polls this endpoint)
                light = bool(msg.get("light"))
                rows = {k: dict(r) for k, r in self.serve_table.items()
                        if not (light and k.startswith("blob:"))}
            conn.send({"rid": msg["rid"], "rows": rows})
        elif t == "oom_clear":
            # agent declined the pick or its kill failed: drop the tag
            self._note_oom_kill(msg["pid"], None,
                                host_id=msg.get("host_id") or HEAD_HOST)
        elif t == "worker_death_reason":
            # direct-dispatch callers ask why their leased worker vanished
            # (e.g. the memory monitor killed it) to build a useful error
            with self.lock:
                w2 = self.workers.get(msg["wid"])
                why = w2.oom_why if self._oom_fresh(w2) else None
            conn.send({"rid": msg["rid"], "reason": why})
        elif t == "direct_lineage":
            # a direct task produced evictable (shm) outputs: retain its spec
            # for reconstruction, same budget as GCS-path tasks
            with self.lock:
                evicted = self._retain_lineage_locked(msg["spec"])
            if evicted:
                self._free_objects(evicted)
        elif t == "unquarantine_chips":
            # operator re-enables chips quarantined by an OOM kill, after
            # confirming the host device pool is healthy again
            with self.lock:
                node = self.nodes.get(msg.get("node_id") or self.local_node_id)
                restored: list[int] = []
                if node is not None:
                    want = msg.get("chips")  # None = all
                    keep: list[int] = []
                    for c in node.quarantined_chips:
                        if want is None or c in want:
                            restored.append(c)
                        else:
                            keep.append(c)
                    node.quarantined_chips = keep
                    node.chip_pool.extend(restored)
            conn.send({"rid": msg["rid"], "restored": restored})
            self._schedule()
        elif t == "will_publish":
            # the sender promises a future object_put for this unpublished
            # direct-task result (publish_on_done). Recording the publisher
            # lets _on_worker_death fail the stub with OwnerDiedError instead
            # of letting borrowers block until their wait timeout
            dead_promise = False
            with self.lock:
                pw = self.workers.get(msg["wid"])
                if pw is None or pw.dead:
                    # promise arrived after the sender was declared dead (its
                    # death scan already ran): fail the stub right away
                    dead_promise = True
                else:
                    e = self.objects.setdefault(
                        msg["oid"], {"status": "pending", "where": None,
                                     "inline": None, "size": 0})
                    if e.get("status") == "pending":
                        e["pub_wid"] = msg["wid"]
                        self._pub_promises.setdefault(
                            msg["wid"], set()).add(msg["oid"])
            if dead_promise:
                self._fail_orphaned_stubs([msg["oid"]])
        elif t == "wait_object":
            self._wait_object(conn, msg)
        elif t == "free_objects_async":
            self._free_objects(list(msg["oids"]))
        elif t == "cancel_task":
            # reference: ray.cancel (core_worker CancelTask) — a queued task
            # is dequeued and its outputs fail with TaskCancelledError; a
            # RUNNING plain task is interrupted only with force=True, by
            # telling its worker process to die over the worker connection
            # (host-agnostic, serializes with completion messages — the same
            # route kill_actor uses). Actor tasks are never force-killed:
            # that would destroy unrelated callers' state (Ray rejects
            # force-cancel on actor tasks too).
            tid = msg["task_id"]
            cancelled = False
            die_conn = None
            free_args: list[str] = []
            with self.lock:
                removed = self.pending_tasks.remove_task_id(tid)
                cancelled = bool(removed)
                for spec in removed:
                    spec["_cancelled"] = True
                if not cancelled:
                    # a pending actor METHOD call sits in its actor's queue,
                    # not pending_tasks — dequeue it there (reference:
                    # ray.cancel dequeues queued actor tasks)
                    for a in self.actors.values():
                        hit = [s for s in a.queue if s["task_id"] == tid]
                        if hit:
                            a.queue = collections.deque(
                                s for s in a.queue if s["task_id"] != tid)
                            for spec in hit:
                                spec["_cancelled"] = True
                                free_args.extend(self._unpin_args_locked(spec))
                                # keep the group-lane backlog counter exact:
                                # a stale positive forces the grouped
                                # dispatch scan on every pass forever
                                if a.method_groups.get(
                                        spec.get("method") or "") is not None:
                                    a.group_queued = max(0, a.group_queued - 1)
                            removed.extend(hit)
                            cancelled = True
                            break
                if not cancelled and msg.get("force"):
                    for w in self.workers.values():
                        spec = w.running_tasks.get(tid)
                        if (spec is not None and not w.dead
                                and spec["kind"] == "task"):
                            # never retried, and fails as cancelled
                            spec["max_retries"] = 0
                            spec["_cancelled"] = True
                            die_conn = w.conn
                            cancelled = True
                            break
            for spec in removed:
                self._fail_task_objects(spec, "task was cancelled")
            if free_args:
                self._free_objects(free_args)
            if die_conn is not None:
                try:
                    die_conn.send({"type": "die"})
                except ConnectionClosed:
                    pass  # already dying; death handler finishes the job
            conn.send({"rid": msg["rid"], "cancelled": cancelled})
        elif t == "free_objects":
            # manual free: drop entries and every host copy, cascading to
            # nested refs (reference: ray._private.internal_api.free)
            self._free_objects(list(msg["oids"]))
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "create_actor":
            err = self._create_actor(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": err is None, "error": err})
        elif t == "actor_task":
            ok, err = self._submit_actor_task(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": ok, "error": err})
        elif t == "actor_task_async":
            # fire-and-forget submission (reference: actor task pushes are
            # async; a dead target fails the RESULT objects so the error
            # surfaces at ray.get, not at .remote())
            spec = msg["spec"]
            ok, _err = self._submit_actor_task(spec)
            if not ok and isinstance(spec.get("num_returns"), int):
                self._fail_task_objects(spec, "actor is dead")
        elif t == "wait_actor_ready":
            self._wait_actor_ready(conn, msg)
        elif t == "actor_info":
            # non-blocking liveness/placement probe (compiled-DAG recovery
            # polls this while waiting out an actor restart): state, the
            # host of the CURRENT incarnation (None mid-restart), and the
            # remaining restart budget
            with self.lock:
                a = self.actors.get(msg["aid"])
                if a is None:
                    conn.send({"rid": msg["rid"], "found": False})
                else:
                    w = self.workers.get(a.worker) if a.worker else None
                    conn.send({
                        "rid": msg["rid"], "found": True, "state": a.state,
                        "host": w.host_id if w is not None else None,
                        "restarts_left": a.restarts_left,
                        "num_restarts": a.num_restarts,
                        "max_task_retries": a.max_task_retries})
        elif t == "get_named_actor":
            with self.lock:
                aid = self.named_actors.get(
                    (msg.get("namespace") or "default", msg["name"]))
                state = self.actors[aid].state if aid else None
            conn.send({"rid": msg["rid"], "aid": aid, "state": state})
        elif t == "kill_actor":
            self._kill_actor(msg["aid"], msg.get("no_restart", True))
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "create_pg":
            err = self._create_pg(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": err is None, "error": err})
        elif t == "remove_pg":
            self._remove_pg(msg["pg_id"])
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "pg_wait":
            self._pg_wait(conn, msg)
        elif t == "pg_table":
            with self.lock:
                table = {
                    pg.pg_id: {
                        "name": pg.name, "state": pg.state, "strategy": pg.strategy,
                        "bundles": [fp.float_dict(b.total) for b in pg.bundles],
                        "bundle_nodes": [b.node_id for b in pg.bundles],
                    }
                    for pg in self.pgs.values()
                }
            conn.send({"rid": msg["rid"], "table": table})
        elif t == "get_named_pg":
            with self.lock:
                pgid = self.named_pgs.get(msg["name"])
            conn.send({"rid": msg["rid"], "pg_id": pgid})
        elif t == "add_node":
            with self.lock:
                node_id = msg["node_id"]
                self.nodes[node_id] = _VNode(node_id, msg["resources"], msg.get("labels"))
                self._reapply_drain_locked(self.nodes[node_id])
            self._emit_event(_const.EVENT_NODE_JOIN, node=node_id,
                             message="virtual node added")
            conn.send({"rid": msg["rid"], "ok": True})
            self._schedule()
        elif t == "remove_node":
            self._remove_node(msg["node_id"], reason="removed by request")
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "node_drain":
            node_id = msg["node_id"]
            grace = msg.get("grace_s")
            reason = msg.get("reason") or ""
            ok, err = True, None
            notify: list = []
            with self.lock:
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    ok, err = False, f"unknown or dead node {node_id!r}"
                else:
                    record = {"node_id": node_id, "reason": reason,
                              "grace_s": grace, "ts": time.time()}
                    # persist BEFORE any side effect (state flip, worker
                    # notices): a GCS restart re-applies the drain instead
                    # of resurrecting the node as placeable
                    if self.storage is not None:
                        self.storage.put("kv", _DRAIN_KV_PREFIX + node_id,
                                         record)
                    self.kv[_DRAIN_KV_PREFIX + node_id] = record
                    if not node.draining:
                        node.draining = True
                        node.drain_reason = reason
                        node.drain_since = time.time()
                        node.drain_grace = grace
                    # fan the notice out to every resident worker (and the
                    # node's host agent) so train sessions can land a
                    # preemption-grace checkpoint inside the window
                    for w in self.workers.values():
                        if w.node_id == node_id and not w.dead:
                            notify.append(w.conn)
                    host_id = self.node_hosts.get(node_id)
                    info = self.hosts.get(host_id) if host_id else None
                    if info is not None and info.get("conn") is not None:
                        notify.append(info["conn"])
            if ok:
                self._emit_event(
                    _const.EVENT_NODE_DRAIN,
                    severity=_const.EVENT_SEVERITY_WARNING, node=node_id,
                    message=f"drain requested: {reason or 'no reason given'}",
                    reason=reason, grace_s=grace)
            push = {"type": "drain_notice", "node_id": node_id,
                    "grace_s": grace, "reason": reason}
            for c in notify:
                try:
                    c.send(push)
                except ConnectionClosed:
                    pass
            conn.send({"rid": msg["rid"], "ok": ok, "error": err})
        elif t == "list_nodes":
            with self.lock:
                nodes = [
                    {"node_id": n.node_id, "alive": n.alive,
                     "draining": n.draining, "labels": dict(n.labels),
                     "drain_reason": n.drain_reason,
                     "drain_since": n.drain_since,
                     "drain_deadline": (n.drain_since + n.drain_grace
                                        if n.draining and n.drain_since
                                        and n.drain_grace else None),
                     "total": fp.float_dict(n.total),
                     "available": fp.float_dict(n.available),
                     "quarantined_chips": list(n.quarantined_chips),
                     "host_view": self._host_view_for(n.node_id)}
                    for n in self.nodes.values()
                ]
            conn.send({"rid": msg["rid"], "nodes": nodes})
        elif t == "kv_put":
            evicted: list[str] = []
            with self.lock:
                self.kv[msg["key"]] = msg["value"]
                if msg["key"].startswith("fn:"):
                    self._fn_access[msg["key"]] = time.monotonic()
                    # function store: bounded LRU-ish (insertion order) so
                    # dynamic-closure workloads can't grow the GCS without
                    # bound (reference: the function table is job-scoped).
                    # Keys referenced by pending/running specs or retained
                    # lineage are pinned — evicting them would make those
                    # tasks permanently unrunnable/unreconstructable. Keys
                    # touched recently are also spared: direct-plane
                    # in-flight specs and drivers inside their existence-
                    # probe memoization window are invisible to the pin
                    # scan, and both resolve within seconds. The budget is
                    # soft — overage with nothing evictable is fine.
                    fn_keys = [k for k in self.kv if k.startswith("fn:")]
                    excess = len(fn_keys) - 2048
                    if excess > 0:
                        pinned = self._pinned_fn_keys_locked()
                        fresh = time.monotonic() - 300.0
                        for k in fn_keys:
                            if excess <= 0:
                                break
                            if (k in pinned
                                    or self._fn_access.get(k, 0.0) > fresh):
                                continue
                            self.kv.pop(k, None)
                            self._fn_access.pop(k, None)
                            evicted.append(k)
                            excess -= 1
            if self.storage is not None:
                self.storage.put("kv", msg["key"], msg["value"])
                for k in evicted:
                    try:
                        self.storage.delete("kv", k)
                    except Exception:
                        pass
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "kv_get":
            with self.lock:
                val = self.kv.get(msg["key"])
                if msg["key"].startswith("fn:") and val is not None:
                    self._fn_access[msg["key"]] = time.monotonic()
            conn.send({"rid": msg["rid"], "value": val})
        elif t == "kv_keys":
            with self.lock:
                keys = [k for k in self.kv if k.startswith(msg.get("prefix", ""))]
                if msg.get("prefix", "").startswith("fn:"):
                    # a driver's existence probe: it will skip re-upload and
                    # submit specs referencing these — keep them evict-safe
                    # through the memoization window
                    now = time.monotonic()
                    for k in keys:
                        self._fn_access[k] = now
            conn.send({"rid": msg["rid"], "keys": keys})
        elif t == "kv_del":
            with self.lock:
                self.kv.pop(msg["key"], None)
                self._fn_access.pop(msg["key"], None)
            if self.storage is not None:
                self.storage.delete("kv", msg["key"])
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "cluster_state":
            with self.lock:
                state = {
                    "total_resources": self.total,
                    "available_resources": self.available,
                    "num_workers": sum(1 for w in self.workers.values() if w.kind == "worker" and not w.dead),
                    "num_actors": sum(1 for a in self.actors.values() if a.state == "alive"),
                    "pending_tasks": len(self.pending_tasks),
                    "task_counter": dict(self.task_counter),
                    "actors": {
                        a.aid: {"state": a.state, "name": a.name, "worker": a.worker,
                                "class": a.create_spec.get("class_name"),
                                "num_restarts": a.num_restarts,
                                "queued": len(a.queue), "in_flight": a.in_flight}
                        for a in self.actors.values()
                    },
                    "nodes": {
                        n.node_id: {"alive": n.alive, "draining": n.draining,
                                    "drain_reason": n.drain_reason,
                                    "drain_since": n.drain_since,
                                    "drain_deadline": (
                                        n.drain_since + n.drain_grace
                                        if n.draining and n.drain_since
                                        and n.drain_grace else None),
                                    "labels": dict(n.labels),
                                    "total": fp.float_dict(n.total),
                                    "available": fp.float_dict(n.available)}
                        for n in self.nodes.values()
                    },
                    # what the scheduler is sitting on, by kind — the
                    # "why is the cluster busy" one-liner for `ray_tpu status`
                    "pending_demand": {
                        "tasks": len(self.pending_tasks),
                        "actor_creations": len(self.pending_actor_creations),
                        "placement_groups": sum(
                            1 for pg in self.pgs.values()
                            if pg.state == "pending"),
                    },
                }
            conn.send({"rid": msg["rid"], "state": state})
        elif t == "resource_demand":
            # unplaceable load summary for the autoscaler (reference: GCS
            # autoscaler state API, gcs_autoscaler_state_manager.h +
            # autoscaler.proto cluster_resource_state)
            with self.lock:
                demands = []
                for spec in self.pending_tasks:
                    demands.append(dict(spec.get("resources") or {}))
                for spec in self.pending_actor_creations:
                    demands.append(dict(spec.get("resources") or {}))
                # direct-dispatch backlogs queued at callers (stale entries
                # age out; dead callers' entries are dropped)
                now_m = time.monotonic()
                for (caller, _rk, _rh), (res, n, ts) in list(
                        self._direct_backlog.items()):
                    w = self.workers.get(caller)
                    if now_m - ts > 5.0 or w is None or w.dead:
                        self._direct_backlog.pop((caller, _rk, _rh), None)
                        continue
                    demands.extend([dict(res)] * min(n, 100))
                pg_demands = []
                for pgid in self.pending_pgs:
                    pg = self.pgs.get(pgid)
                    if pg is not None and pg.state == "pending":
                        pg_demands.append({"strategy": pg.strategy,
                                           "bundles": [fp.float_dict(b.total)
                                                       for b in pg.bundles]})
                state = {
                    "demands": demands,
                    "pg_demands": pg_demands,
                    "total_resources": self.total,
                    "available_resources": self.available,
                    "num_nodes": sum(1 for n in self.nodes.values() if n.alive),
                    "node_ids": [n.node_id for n in self.nodes.values()
                                 if n.alive],
                }
            conn.send({"rid": msg["rid"], "demand": state})
        elif t == "worker_stacks":
            # live thread stacks of one worker process (reference:
            # dashboard/modules/reporter on-demand profiling)
            self._park_relay(conn, msg, prefix="st",
                             payload={"type": "dump_stacks"})
        elif t == "worker_profile":
            # on-demand in-process sampling profiler (reference capability:
            # dashboard/modules/reporter's py-spy integration; here the
            # worker samples its own frames — no ptrace in the sandbox).
            # Sampling runs duration_s in the worker, so the parked waiter
            # gets a TTL that outlives it.
            # sanitize HERE, not just at the dashboard edge: NaN survives
            # min()/comparisons, so a NaN duration from any client would
            # make the relay TTL never expire and leak the parked waiter
            import math as _math

            dur = float(msg.get("duration_s", 5.0))
            hz = float(msg.get("hz", 50.0))
            if not _math.isfinite(dur) or dur <= 0:
                dur = 5.0
            if not _math.isfinite(hz) or hz <= 0:
                hz = 50.0
            self._park_relay(
                conn, msg, prefix="pf",
                ttl=min(dur, 120.0) + 30.0,
                payload={"type": "profile", "duration_s": min(dur, 120.0),
                         "hz": hz})
        elif t == "stacks_reply":
            with self.lock:
                waiter = self._tensor_exports.pop(msg["token"], None)
            if waiter is not None:
                try:
                    waiter[0].send({"rid": waiter[1], "ok": True,
                                    "stacks": msg.get("text", "")})
                except ConnectionClosed:
                    pass
        elif t == "list_objects":
            # object-directory summary (reference: `ray list objects`,
            # util/state/state_cli.py backed by GCS/raylet introspection).
            # Filters run BEFORE the limit cut (state.list_objects pushes
            # its predicates here): limiting first would return fewer than
            # `limit` matching rows while more matches exist, and shipping
            # the whole table for client-side filtering would marshal
            # every row under this lock. limit <= 0 means unbounded.
            from ray_tpu.util.state import matches_filters

            limit = int(msg.get("limit", 1000))
            filters = msg.get("filters") or ()
            truncated = False
            with self.lock:
                total = len(self.objects)
                rows = []
                for oid, e in self.objects.items():
                    row = {
                        "object_id": oid, "status": e.get("status"),
                        "where": e.get("where"), "size": e.get("size", 0),
                        "ref_count": e.get("count", 0),
                        "sys_holds": e.get("sys", 0),
                        "pinned": bool(e.get("pinned")),
                        "hosts": sorted(e.get("hosts", ())),
                    }
                    if filters and not matches_filters(row, filters):
                        continue
                    if 0 < limit <= len(rows):
                        # a further MATCH exists past the cut
                        truncated = True
                        break
                    rows.append(row)
            conn.send({"rid": msg["rid"], "objects": rows, "total": total,
                       "truncated": truncated})
        elif t == "list_workers":
            with self.lock:
                rows = [{"wid": w.wid, "pid": w.pid, "kind": w.kind,
                         "node_id": w.node_id, "host": w.host_id,
                         "dead": w.dead, "idle": w.idle,
                         "actor_id": w.actor_id,
                         "tpu_chips": list(w.tpu_chips)}
                        for w in self.workers.values()]
            conn.send({"rid": msg["rid"], "workers": rows})
        elif t == "export_tensor":
            # RDT cross-process fetch: relay to the owner worker and park
            # the requester until export_tensor_done (reference: RDT
            # transport coordination, gpu_object_manager.py)
            with self.lock:
                owner = self.workers.get(msg["owner_wid"])
                if owner is None or owner.dead:
                    owner = None
                else:
                    token = f"tx-{msg['rid']}-{id(conn) & 0xffffff}"
                    self._tensor_exports[token] = (conn, msg["rid"],
                                                   msg["owner_wid"],
                                                   time.monotonic())
            if owner is None:
                conn.send({"rid": msg["rid"], "ok": False,
                           "error": "owner process is gone"})
            else:
                try:
                    owner.conn.send({"type": "do_export_tensor",
                                     "tensor_id": msg["tensor_id"],
                                     "token": token})
                except ConnectionClosed:
                    with self.lock:
                        self._tensor_exports.pop(token, None)
                    conn.send({"rid": msg["rid"], "ok": False,
                               "error": "owner connection lost"})
        elif t == "export_tensor_done":
            with self.lock:
                waiter = self._tensor_exports.pop(msg["token"], None)
            if waiter is not None:
                wconn, wrid = waiter[0], waiter[1]
                try:
                    if msg.get("oid"):
                        wconn.send({"rid": wrid, "ok": True,
                                    "oid": msg["oid"]})
                    else:
                        wconn.send({"rid": wrid, "ok": False,
                                    "error": msg.get("error")
                                    or "tensor not found in owner registry"})
                except ConnectionClosed:
                    pass
        elif t == "metrics_report":
            # per-source replace so a worker's repeated reports (cumulative
            # local values) don't double-count in the aggregate
            source = msg.get("source") or wid or "unknown"
            with self.lock:
                for m in msg.get("metrics", []):
                    rec = self.metrics.setdefault(
                        m["name"], {"kind": m["kind"],
                                    "description": m.get("description", ""),
                                    "series": {}, "ts": {}})
                    rec["series"][source] = m["series"]
                    # snapshot ts per source: gauge merging picks the
                    # newest series deterministically (util/metrics.py
                    # to_prometheus), not whichever source iterates last
                    rec.setdefault("ts", {})[source] = m.get(
                        "ts", time.time())
        elif t == "metrics_history":
            # retained time series for the dashboard's graphs: per-node
            # resource views + cluster-level gauges (reference capability:
            # dashboard metrics tab backed by Prometheus range queries)
            with self.lock:
                limit = int(msg.get("limit", 0)) or None
                nodes = {hid: list(dq)[-limit:] if limit else list(dq)
                         for hid, dq in self.node_history.items()}
                cluster = (list(self.cluster_history)[-limit:] if limit
                           else list(self.cluster_history))
            conn.send({"rid": msg["rid"], "nodes": nodes,
                       "cluster": cluster})
        elif t == "metrics_snapshot":
            with self.lock:
                snap = {name: {"kind": r["kind"],
                               "description": r["description"],
                               "series": {s: list(v) for s, v in r["series"].items()},
                               "ts": dict(r.get("ts") or {})}
                        for name, r in self.metrics.items()}
                # fold in internal runtime stats as gauges
                snap["ray_tpu_pending_tasks"] = {
                    "kind": "gauge", "description": "tasks queued in the GCS",
                    "series": {"gcs": [[[], float(len(self.pending_tasks))]]}}
                snap["ray_tpu_live_actors"] = {
                    "kind": "gauge", "description": "actors in state alive",
                    "series": {"gcs": [[[], float(sum(
                        1 for a in self.actors.values() if a.state == "alive"))]]}}
                snap["ray_tpu_object_store_bytes"] = {
                    "kind": "gauge", "description": "live shm bytes per host",
                    "series": {"gcs": [[[["host", h]], float(v)]
                                       for h, v in self.host_shm_bytes.items()]}}
                snap["ray_tpu_live_workers"] = {
                    "kind": "gauge", "description": "live worker processes",
                    "series": {"gcs": [[[], float(sum(
                        1 for w in self.workers.values()
                        if w.kind == "worker" and not w.dead))]]}}
                self._draining_gauge.set(float(sum(
                    1 for n in self.nodes.values()
                    if n.alive and n.draining)))
                snap["ray_tpu_nodes_draining"] = {
                    "kind": "gauge",
                    "description": self._draining_gauge.description,
                    "series": {"gcs": self._draining_gauge._snapshot_series()}}
                snap["ray_tpu_node_mem_usage"] = {
                    "kind": "gauge",
                    "description": "host memory usage fraction per node",
                    "series": {"gcs": [
                        [[["host", hid]], float(s[-1]["mem_usage"] or 0.0)]
                        for hid, s in self.node_history.items() if s]}}
                for k, v in self.task_counter.items():
                    snap.setdefault("ray_tpu_tasks_total", {
                        "kind": "counter",
                        "description": "task terminal states",
                        "series": {"gcs": []}})["series"]["gcs"].append(
                            [[["state", k]], float(v)])
                # server-side RPC latency: unregistered histogram folded in
                # under the reserved "gcs" source (see __init__)
                snap["ray_tpu_gcs_rpc_seconds"] = {
                    "kind": "histogram",
                    "description": self._rpc_hist.description,
                    "series": {"gcs": self._rpc_hist._snapshot_series()},
                    "ts": {"gcs": time.time()}}
                # scheduler decision attribution (unregistered, GCS-local)
                self._sched_pending_gauge.set(
                    float(len(self.pending_tasks)), tags={"kind": "task"})
                self._sched_pending_gauge.set(
                    float(len(self.pending_actor_creations)),
                    tags={"kind": "actor"})
                self._sched_pending_gauge.set(
                    float(sum(1 for pg in self.pgs.values()
                              if pg.state == "pending")), tags={"kind": "pg"})
                for name, obj in (
                        ("ray_tpu_sched_decision_seconds", self._sched_hist),
                        ("ray_tpu_sched_decisions_total", self._sched_counter),
                        ("ray_tpu_sched_pending", self._sched_pending_gauge)):
                    snap[name] = {
                        "kind": obj.kind, "description": obj.description,
                        "series": {"gcs": obj._snapshot_series()},
                        "ts": {"gcs": time.time()}}
            conn.send({"rid": msg["rid"], "metrics": snap})
        elif t == "events_report":
            with self.lock:
                for ev in msg.get("events", []):
                    ev.setdefault("worker_id", wid or "")
                    self.task_events.append(ev)
                    if ev.get("direct") and ev.get("name") != "actor_create":
                        # direct-dispatch tasks never pass through
                        # submit/task_done: account them here so cluster
                        # task counters (and the errors channel) stay truthful
                        self.task_counter["submitted"] += 1
                        self.task_counter[
                            "finished" if ev.get("ok") else "failed"] += 1
                        if not ev.get("ok"):
                            self.publish("errors", {
                                "task_id": ev.get("task_id"), "kind": "task",
                                "name": ev.get("name"),
                                "worker": ev.get("worker_id"),
                                "error": ev.get("error"), "ts": ev.get("end")})
        elif t == "task_events":
            with self.lock:
                events = list(self.task_events)
            conn.send({"rid": msg["rid"], "events": events})
        elif t == "request_log_report":
            # serve flight-recorder entries (no reply — fire-and-forget
            # like events_report; the flusher bounds each batch to the
            # sender's ring size)
            with self.lock:
                for rec in msg.get("entries", []):
                    rec.setdefault("source", msg.get("source", wid or ""))
                    self.request_log.append(rec)
        elif t == "list_requests":
            with self.lock:
                rows = [dict(r) for r in self.request_log]
            limit = int(msg.get("limit", 0) or 0)
            if limit:
                rows = rows[-limit:]
            conn.send({"rid": msg["rid"], "requests": rows})
        elif t == "cluster_events_report":
            # controller processes (serve/train) flushing their local event
            # rings (no reply — fire-and-forget like request_log_report)
            if self._events_enabled:
                src = str(msg.get("source") or wid or "")
                for ev in msg.get("events", []):
                    if src and not ev.get("source"):
                        ev["source"] = src
                    self._ingest_event(dict(ev))
        elif t == "list_events":
            from ray_tpu._private import events as _events
            with self._events_lock:
                rows = [dict(r) for r in self.cluster_events]
            rows = _events.filter_events(
                rows,
                min_severity=str(msg.get("severity") or ""),
                etype=str(msg.get("etype") or ""),
                node=str(msg.get("node") or ""),
                after_seq=int(msg.get("after_seq", 0) or 0),
                limit=int(msg.get("limit", 0) or 0))
            conn.send({"rid": msg["rid"], "events": rows})
        elif t == "sched_explain":
            conn.send({"rid": msg["rid"],
                       **self._sched_explain(str(msg.get("target") or ""))})
        elif t == "dag_register":
            # compiled-DAG registry (tentpole: observability for the channel
            # execution plane). The registering connection's wid is recorded
            # so driver death retires the entry — a DAG cannot outlive the
            # driver that owns its channels.
            rec = dict(msg["dag"])
            rec.setdefault("driver_wid", wid or "")
            with self.lock:
                self.compiled_dags[str(rec["dag_id"])] = rec
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "dag_deregister":
            with self.lock:
                existed = self.compiled_dags.pop(
                    str(msg["dag_id"]), None) is not None
            conn.send({"rid": msg["rid"], "ok": True, "existed": existed})
        elif t == "dag_list":
            with self.lock:
                rows = [dict(r) for r in self.compiled_dags.values()]
            conn.send({"rid": msg["rid"], "dags": rows})
        elif t == "subscribe":
            key = (msg["channel"], msg["sub_id"])
            with self.lock:
                self.pubsub_queues.setdefault(key, collections.deque(maxlen=10000))
                self.pubsub_conns[key] = conn
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "unsubscribe":
            key = (msg["channel"], msg["sub_id"])
            with self.lock:
                self.pubsub_queues.pop(key, None)
                self.pubsub_conns.pop(key, None)
                poller = self.pubsub_pollers.pop(key, None)
            if poller is not None:
                try:
                    poller[0].send({"rid": poller[1], "items": [], "closed": True})
                except ConnectionClosed:
                    pass
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "publish":
            self.publish(msg["channel"], msg["data"])
        elif t == "pubsub_poll":
            key = (msg["channel"], msg["sub_id"])
            with self.lock:
                q = self.pubsub_queues.get(key)
                if q is None:
                    conn.send({"rid": msg["rid"], "items": [], "closed": True})
                elif q:
                    items = list(q)
                    q.clear()
                    conn.send({"rid": msg["rid"], "items": items})
                else:
                    # long-poll: park until the next publish on the channel
                    # (reference: pubsub long-poll, src/ray/pubsub/publisher.h)
                    self.pubsub_pollers[key] = (conn, msg["rid"])
        else:
            logger.warning("gcs: unknown message type %s", t)
        return wid

    def publish(self, channel: str, data) -> None:
        """Fan a message out to every subscriber of `channel`. Callers may
        hold self.lock: sends happen on the pubsub sender thread."""
        with self.lock:
            for (ch, sub), q in self.pubsub_queues.items():
                if ch != channel:
                    continue
                key = (ch, sub)
                poller = self.pubsub_pollers.pop(key, None)
                if poller is not None:
                    self._pub_sendq.put((poller[0], {"rid": poller[1],
                                                     "items": [data]}))
                else:
                    q.append(data)

    def _pub_send_loop(self):
        while True:
            item = self._pub_sendq.get()
            if item is None:
                return
            conn, msg = item
            try:
                conn.send(msg)
            except (ConnectionClosed, Exception):
                pass

    # --------------------------------------------------------- cluster events

    def _emit_event(self, etype: str, *, severity: str | None = None,
                    node: str = "", message: str = "", **fields) -> None:
        """Record one typed cluster event at its GCS source. The event type
        must be a constants.py EVENT_* name (event-type-literal check).
        Callers may hold self.lock: the ring has its own lock and the
        sqlite write keys on a unique seq, so ordering never inverts."""
        if not self._events_enabled:
            return
        from ray_tpu._private.events import make_event

        rec = make_event(
            etype, severity=severity or _const.EVENT_SEVERITY_INFO,
            node=node, message=message, source="gcs", **fields)
        self._ingest_event(rec)

    def _ingest_event(self, rec: dict) -> None:
        """Stamp a GCS sequence number onto one event record, ring it, and
        write INFO+ through to the sqlite `events` table (DEBUG events —
        lease churn — stay in-memory: they dominate volume and explain
        nothing after a restart)."""
        with self._events_lock:
            self._cluster_event_seq += 1
            seq = rec[_const.EVENT_FIELD_SEQ] = self._cluster_event_seq
            self.cluster_events.append(rec)
        if (self.storage is not None
                and rec.get(_const.EVENT_FIELD_SEVERITY)
                != _const.EVENT_SEVERITY_DEBUG):
            try:
                self.storage.put("events", f"{seq:012d}", rec)
                # bound the table to the ring size: the entry this one
                # rotated out of a full ring also leaves the table
                if seq > self._events_ring_size:
                    self.storage.delete(
                        "events", f"{seq - self._events_ring_size:012d}")
            except Exception:
                # persistence is best-effort; the ring stays truthful
                logger.warning("gcs: event persist failed for seq %d", seq,
                               exc_info=True)

    def _restore_events_from_storage(self) -> None:
        """Reload persisted events (oldest first) and resume the sequence
        counter past them so post-restart events sort after."""
        rows = sorted(self.storage.items("events"))
        with self._events_lock:
            for key, rec in rows[-self._events_ring_size:]:
                self.cluster_events.append(rec)
            if rows:
                self._cluster_event_seq = max(
                    self._cluster_event_seq, int(rows[-1][0]))

    # ------------------------------------------------- scheduler attribution

    def _observe_sched(self, kind: str, outcome: str,
                       seconds: float | None, n: int = 1) -> None:
        """One terminal scheduler decision (n for batched grants):
        decisions/s counter plus the decision-latency histogram when a
        wait/RTT is attributable."""
        tags = {"kind": kind, "outcome": outcome}
        self._sched_counter.inc(float(n), tags=tags)
        if seconds is not None and seconds >= 0:
            self._sched_hist.observe(seconds, tags=tags)

    def _trace_enqueue(self, key: str, kind: str) -> None:
        """(Re)enter a work item into the pending decision-trace table.
        Caller holds self.lock."""
        tr = self.sched_traces.get(key)
        if tr is None:
            tr = self.sched_traces[key] = {
                "kind": kind, "attempts": 0, "history": []}
        tr["status"] = "pending"
        tr["attempts"] += 1
        tr["enqueued_ts"] = time.time()
        tr["_enq_mono"] = time.monotonic()

    def _trace_decision(self, key: str, status: str, **fields) -> None:
        """Advance a trace to dispatched/placed/created/failed, recording
        per-attempt attribution. Caller holds self.lock."""
        tr = self.sched_traces.get(key)
        if tr is None:
            return
        tr["status"] = status
        tr.update(fields)
        if status in ("placed", "created", "failed"):
            hist = tr.setdefault("history", [])
            hist.append({k: tr.get(k) for k in
                         ("attempts", "status", "node", "queue_wait_s",
                          "lease_rtt_s") if tr.get(k) is not None})
            del hist[:-8]  # keep the last attempts only

    def _explain_spec_locked(self, spec: dict) -> dict:
        """Per-node rejection table for one pending spec: mirrors _fits_for
        but returns WHY each candidate fails instead of the first fit.
        Computed lazily (only when sched_explain asks) so _schedule never
        pays for it. Caller holds self.lock."""
        res = self._spec_fp(spec)
        strat = spec.get("strategy") or {}
        reasons: dict[str, str] = {}
        if strat.get("kind") == "pg":
            pg = self.pgs.get(strat.get("pg_id"))
            if pg is None:
                return {"<pg>": f"no such placement group {strat.get('pg_id')!r}"}
            if pg.state != "created":
                return {"<pg>": f"placement group is {pg.state}, not created"}
            idx = strat.get("bundle", -1)
            cand = (list(enumerate(pg.bundles)) if idx == -1
                    else [(idx, pg.bundles[idx])])
            for i, b in cand:
                short = next((k for k, v in res.items()
                              if b.available.get(k, 0) < v), None)
                if short is None:
                    reasons[f"bundle[{i}]@{b.node_id}"] = (
                        "fits; waiting on worker availability")
                else:
                    reasons[f"bundle[{i}]@{b.node_id}"] = (
                        f"insufficient {short}: need "
                        f"{fp.from_fp(res[short])}, bundle has "
                        f"{fp.from_fp(b.available.get(short, 0))}")
            return reasons
        hard = strat.get("hard", {}) if strat.get("kind") == "node_label" else {}
        affinity = (strat.get("node_id")
                    if strat.get("kind") == "node_affinity" else None)
        soft = bool(strat.get("soft"))
        for n in self.nodes.values():
            if not n.alive:
                reasons[n.node_id] = "node is dead"
                continue
            if n.draining:
                reasons[n.node_id] = (
                    "node is draining"
                    + (f" ({n.drain_reason})" if n.drain_reason else ""))
                continue
            if affinity is not None and n.node_id != affinity and not soft:
                reasons[n.node_id] = (
                    f"not the node_affinity target {affinity!r}")
                continue
            miss = next(((k, v) for k, v in hard.items()
                         if n.labels.get(k) != v), None)
            if miss is not None:
                reasons[n.node_id] = (
                    f"label mismatch: requires {miss[0]}={miss[1]!r}, node "
                    f"has {n.labels.get(miss[0])!r}")
                continue
            short = next((k for k, v in res.items()
                          if n.available.get(k, 0) < v), None)
            if short is not None:
                reasons[n.node_id] = (
                    f"insufficient {short}: need {fp.from_fp(res[short])}, "
                    f"node has {fp.from_fp(n.available.get(short, 0))} "
                    f"available of {fp.from_fp(n.total.get(short, 0))}")
                continue
            reasons[n.node_id] = (
                "fits; waiting on worker availability (spawn in progress "
                "or max_workers reached)")
        if not self._deps_ready(spec):
            reasons["<deps>"] = "task dependencies are not yet available"
        return reasons

    def _explain_pg_locked(self, pg: "_PG") -> dict:
        """Per-node rejection view for a pending placement group: what the
        placement policy could fit on each node in isolation (bundles that
        fit nowhere, or a strategy that needs a joint assignment no node
        set satisfies). Caller holds self.lock."""
        reasons: dict[str, str] = {}
        for n in self.nodes.values():
            if not n.alive:
                reasons[n.node_id] = "node is dead"
                continue
            if n.draining:
                reasons[n.node_id] = (
                    "node is draining"
                    + (f" ({n.drain_reason})" if n.drain_reason else ""))
                continue
            unfit = []
            for i, b in enumerate(pg.bundles):
                short = next((k for k, v in b.total.items()
                              if n.available.get(k, 0) < v), None)
                if short is not None:
                    unfit.append(
                        f"bundle[{i}] short {fp.from_fp(b.total[short] - n.available.get(short, 0))} {short}")
            if unfit:
                reasons[n.node_id] = "; ".join(unfit)
            else:
                reasons[n.node_id] = (
                    f"every bundle fits individually; no joint "
                    f"{pg.strategy} assignment found yet")
        return reasons

    def _sched_explain(self, target: str) -> dict:
        """Answer "why is X pending": the live per-node rejection table for
        a pending actor or placement group, plus the decision trace for
        anything the scheduler has already placed."""
        with self.lock:
            a = self.actors.get(target)
            if a is not None:
                out = {"found": True, "kind": "actor", "state": a.state,
                       "trace": dict(self.sched_traces.get(target) or {})}
                out["trace"].pop("_enq_mono", None)
                if a.state in ("pending", "restarting"):
                    spec = next(
                        (s for s in self.pending_actor_creations
                         if s.get("actor_id") == target), None)
                    if spec is not None:
                        out["rejections"] = self._explain_spec_locked(spec)
                        enq = spec.get("_enq_ts")
                        if enq is not None:
                            out["queue_wait_s"] = round(
                                time.monotonic() - enq, 6)
                    else:
                        # dispatched: a worker is spawning / creating it
                        out["rejections"] = {}
                        out["note"] = ("creation dispatched to worker "
                                       f"{a.worker!r}; waiting on the "
                                       "worker to finish __init__")
                return out
            pg = self.pgs.get(target)
            if pg is not None:
                out = {"found": True, "kind": "pg", "state": pg.state,
                       "trace": dict(self.sched_traces.get(target) or {})}
                out["trace"].pop("_enq_mono", None)
                if pg.state == "pending":
                    out["rejections"] = self._explain_pg_locked(pg)
                return out
        return {"found": False,
                "error": f"no actor or placement group {target!r}"}

    # --------------------------------------------------------------- objects

    def _on_object_ready(self, oid: str, where: str, inline, size: int,
                         is_error: bool, host: str = HEAD_HOST,
                         pin: bool = False, contained=None,
                         tier: str = "shm", only_if_pending: bool = False):
        with self.lock:
            prev = self.objects.get(oid)
            if (only_if_pending and prev is not None
                    and prev.get("status") != "pending"):
                # guarded write (owner-death error path): a concurrently
                # published real value wins over the OwnerDiedError
                return
            if (prev is not None and prev["status"] == "ready"
                    and prev["where"] == "shm" and where == "shm"):
                # an additional shm copy on another host: extend the location
                # set, keep the entry (reference: object directory adding a
                # location, ownership_object_directory.h)
                added_copy = False
                if host not in prev.setdefault("hosts", set()):
                    prev["hosts"].add(host)
                    if tier == "shm":
                        self._note_shm_copy_locked(prev, host)
                        added_copy = True
            else:
                added_copy = None
        if added_copy is not None:
            if added_copy:
                # pull-heavy consumer hosts must hit the spill budget too
                self._maybe_spill(host)
            return
        with self.lock:
            prev = self.objects.get(oid)
            if (only_if_pending and prev is not None
                    and prev.get("status") != "pending"):
                return  # re-check: the real publish won the race
            if prev is not None:
                self._drop_shm_copies_locked(prev)  # stale copies of an overwrite
                pw = prev.pop("pub_wid", None)
                if pw is not None:
                    # promise fulfilled (or superseded): drop the index entry
                    # so long-lived drivers don't accumulate dead promises
                    s = self._pub_promises.get(pw)
                    if s is not None:
                        s.discard(oid)
                        if not s:
                            self._pub_promises.pop(pw, None)
            entry = self.objects[oid] = {
                **(prev or {}),  # keep refcount state accumulated while pending
                "status": "error" if is_error else "ready",
                "where": where,
                "inline": inline,
                "size": size,
                "hosts": {host} if where == "shm" else set(),
            }
            if where == "shm":
                entry["shm_live"] = set()
                if tier == "shm":
                    self._note_shm_copy_locked(entry, host)
            if pin:
                entry["pinned"] = True
            if contained and "contained" not in entry:
                entry["contained"] = list(contained)
                self._sys_hold_locked(contained, +1)
            waiters = self.object_waiters.pop(oid, [])
        for conn, rid in waiters:
            self._reply_object(conn, rid, entry)
        if where == "shm" and tier == "shm":
            self._maybe_spill(host)
        self._schedule()

    def _note_shm_copy_locked(self, entry: dict, host: str) -> None:
        entry.setdefault("shm_live", set()).add(host)
        entry["last_access"] = time.monotonic()
        self.host_shm_bytes[host] += entry.get("size", 0)

    def _drop_shm_copies_locked(self, entry: dict) -> None:
        """Undo the tmpfs accounting for every live copy of an entry (host
        loss, reconstruction reset, entry overwrite)."""
        for h in entry.get("shm_live", ()):
            self.host_shm_bytes[h] -= entry.get("size", 0)
        entry["shm_live"] = set()

    def _maybe_spill(self, host: str) -> None:
        """Spill LRU tmpfs objects on `host` down to disk until under the
        budget (reference: raylet/local_object_manager.h:43)."""
        if not self.spill_capacity:
            return
        to_spill: list[str] = []
        with self.lock:
            used = self.host_shm_bytes.get(host, 0)
            if used <= self.spill_capacity:
                return
            target = int(self.spill_capacity * 0.7)
            cands = sorted(
                (e.get("last_access", 0.0), oid, e)
                for oid, e in self.objects.items()
                if e.get("status") == "ready" and host in e.get("shm_live", ()))
            for _, oid, e in cands:
                if used <= target:
                    break
                e["shm_live"].discard(host)
                used -= e.get("size", 0)
                to_spill.append(oid)
            self.host_shm_bytes[host] = used
            agent = (self.hosts.get(host) or {}).get("conn")
        if not to_spill:
            return
        if agent is not None:
            try:
                agent.send({"type": "spill_objects", "oids": to_spill})
            except ConnectionClosed:
                pass
        elif self.session_id:
            for oid in to_spill:
                try:
                    self._head_store().spill(oid)
                except Exception:
                    logger.exception("spill of %s failed", oid)

    def _object_locations_locked(self, entry: dict) -> list:
        return [(h, self.hosts[h]["object_addr"])
                for h in entry.get("hosts", ()) if h in self.hosts]

    # ---------------------------------------------------- reference counting
    # GCS-arbitered equivalent of the reference's distributed ReferenceCounter
    # (src/ray/core_worker/reference_counter.h:43): workers report process-
    # level ref transitions; the GCS adds system holds for in-flight task
    # dependencies and refs nested inside stored objects, and frees an object
    # cluster-wide when every hold is gone.

    def _on_ref_delta(self, deltas: dict, wid: str | None = None):
        free: list[str] = []
        with self.lock:
            w = self.workers.get(wid) if wid else None
            if w is not None and w.dead:
                # this process was already declared dead and its ref balance
                # reclaimed — applying its late in-flight deltas would double
                # count (e.g. a -1 drained from the socket after host removal)
                return
            for oid, n in deltas.items():
                e = self.objects.get(oid)
                if e is None:
                    continue  # stale ref from a prior session / already freed
                e["count"] = e.get("count", 0) + n
                # any delta (including a within-window +1/-1 cancel, sent as
                # 0) proves the object has been user-referenced
                e["counted"] = True
                if w is not None and n:
                    bal = w.ref_balance.get(oid, 0) + n
                    if bal:
                        w.ref_balance[oid] = bal
                    else:
                        w.ref_balance.pop(oid, None)
                if self._freeable_locked(oid, e):
                    free.append(oid)
        if free:
            self._free_objects(free)

    def _freeable_locked(self, oid: str, e: dict) -> bool:
        return (e.get("counted", False)
                and e.get("count", 0) <= 0
                and e.get("sys", 0) <= 0
                and not e.get("pinned", False)
                and e.get("status") != "pending"
                # PG-ready sentinels are owned by the PG state machine
                and not (oid.endswith("r0000") and oid[:-5] in self.pgs))

    def _sys_hold_locked(self, oids, n: int) -> list[str]:
        """Adjust system holds; returns oids that became freeable."""
        out = []
        for oid in oids:
            e = self.objects.get(oid)
            if e is None:
                if n > 0:
                    # dep/nested ref the GCS hasn't seen yet — typically an
                    # unpublished direct-task result whose owner will
                    # object_put it (publish_on_done): park the hold in a
                    # stub entry the publish merges into
                    self.objects[oid] = {"status": "pending", "where": None,
                                         "inline": None, "size": 0, "sys": n}
                continue
            e["sys"] = e.get("sys", 0) + n
            if n < 0 and self._freeable_locked(oid, e):
                out.append(oid)
        return out

    def _unpin_args_locked(self, spec: dict) -> list[str]:
        """Release a spec's pinned args blob (no user ref ever exists for
        one); returns the oid to free, if any."""
        args_oid = spec.get("args_oid")
        if args_oid and args_oid in self.objects:
            self.objects[args_oid]["pinned"] = False
            return [args_oid]
        return []

    def _actor_dead_cleanup_locked(self, create_spec: dict) -> list[str]:
        """Permanent actor death: release creation-arg holds and the pinned
        creation-args blob. Returns oids to free."""
        out = self._sys_hold_locked(create_spec.pop("_actor_holds", ()), -1)
        out.extend(self._unpin_args_locked(create_spec))
        return out

    def _drop_lineage_locked(self, tid: str) -> list[str]:
        """Forget a task's retained spec; its (pinned, otherwise-unowned)
        args blob goes with it. Returns oids to free."""
        spec = self.lineage.pop(tid, None)
        if spec is None:
            return []
        return self._unpin_args_locked(spec)

    def _on_objects_evicted(self, host: str, oids: list) -> None:
        """A host's arena pushed these objects down to its spill tier to
        make room: drop them from that host's tmpfs accounting so
        `_maybe_spill` and the object directory's tier info stay truthful.
        The host keeps serving them (spill-tier reads are transparent), so
        the location set is untouched."""
        with self.lock:
            for oid in oids:
                e = self.objects.get(oid)
                if e is not None and host in e.get("shm_live", ()):
                    e["shm_live"].discard(host)
                    self.host_shm_bytes[host] -= e.get("size", 0)

    def _head_store(self):
        if getattr(self, "_head_store_obj", None) is None:
            if self.stopped:
                # a straggler thread lazily constructing the store AFTER
                # session teardown would recreate the just-unlinked arena
                # segment in /dev/shm — refuse instead (callers tolerate)
                raise RuntimeError("GCS stopped; head store torn down")
            from ray_tpu._private.object_store import make_object_store

            self._head_store_obj = make_object_store(self.session_id)
            if hasattr(self._head_store_obj, "on_evict"):
                # the GCS runs in the driver process: account directly
                self._head_store_obj.on_evict = (
                    lambda oids: self._on_objects_evicted(HEAD_HOST, oids))
        return self._head_store_obj

    def _free_objects(self, oids: list[str]):
        """Drop entries and delete every host's shm copy; cascades to refs
        nested inside the freed objects (reference: plasma delete +
        reference_counter release cascades)."""
        by_host: dict[str, list[str]] = collections.defaultdict(list)
        cascade: list[str] = []
        agent_msgs = []
        dev_frees: dict = collections.defaultdict(list)  # wid → tensor ids
        with self.lock:
            for oid in oids:
                e = self.objects.pop(oid, None)
                if e is None:
                    continue
                dt = e.get("device_tensors")
                if dt:
                    dev_frees[dt[0]].extend(dt[1])
                self.object_waiters.pop(oid, None)
                self._drop_shm_copies_locked(e)
                for h in e.get("hosts", ()):
                    by_host[h].append(oid)
                cascade.extend(self._sys_hold_locked(e.get("contained", ()), -1))
                # drop retained lineage once a task's outputs are all gone
                tid = oid[:-5]
                spec = self.lineage.get(tid)
                if spec is not None and not any(
                        f"{tid}r{i:04d}" in self.objects
                        for i in range(spec["num_returns"])):
                    cascade.extend(self._drop_lineage_locked(tid))
            for h, lst in by_host.items():
                info = self.hosts.get(h)
                if info is not None and info.get("conn") is not None:
                    agent_msgs.append((info["conn"], lst))
        if self.session_id:
            for oid in by_host.get(HEAD_HOST, ()):
                try:
                    self._head_store().delete(oid)
                except Exception:
                    pass
        for conn, lst in agent_msgs:
            try:
                conn.send({"type": "delete_objects", "oids": lst})
            except ConnectionClosed:
                pass
        if dev_frees:
            # tell owners to drop the freed objects' HBM registry entries
            with self.lock:
                targets = [(self.workers.get(w), tids)
                           for w, tids in dev_frees.items()]
            for w, tids in targets:
                if w is not None and not w.dead:
                    try:
                        w.conn.send({"type": "free_device_tensors",
                                     "tensor_ids": tids})
                    except ConnectionClosed:
                        pass
        if cascade:
            self._free_objects(cascade)

    # ------------------------------------------------- lineage reconstruction

    def _delete_host_copy(self, oid: str, host: str) -> None:
        """Delete one host's store copy of an object with no table entry."""
        info = self.hosts.get(host)
        if info is not None and info.get("conn") is not None:
            try:
                info["conn"].send({"type": "delete_objects", "oids": [oid]})
            except ConnectionClosed:
                pass
        elif host == HEAD_HOST and self.session_id:
            try:
                self._head_store().delete(oid)
            except Exception:
                pass

    def _answer_stream_next(self, conn: MsgConnection, rid: int,
                            task_id: str, index: int) -> None:
        with self.lock:
            st = self.streams.get(task_id)
            if st is None:
                reply = {"rid": rid, "done": True, "error": None}
            elif index < len(st["items"]):
                reply = {"rid": rid, "oid": st["items"][index]}
            elif st["done"]:
                reply = {"rid": rid, "done": True, "error": st["error"]}
            else:
                st["waiters"].append((conn, rid, index))
                return
        try:
            conn.send(reply)
        except ConnectionClosed:
            pass

    def _reconstruct_or_report(self, oid: str) -> str:
        """A consumer failed to materialize `oid` from any advertised copy.
        Resubmit the creating task — and, recursively, any upstream task
        whose outputs it needs that are also gone — if specs were retained
        (reference: object_recovery_manager.h:41 — the owner resubmits the
        creating task; lineage pinning keeps ancestors recoverable).
        Returns the action the consumer should take."""
        plan: list[dict] = []
        with self.lock:
            e = self.objects.get(oid)
            tid = oid[:-5] if len(oid) > 5 else ""
            if e is None:
                # no entry yet the owner asserts loss: an UNPUBLISHED
                # direct-task result (owned bookkeeping never reached the
                # GCS). The retained lineage spec can still replay it —
                # _collect_recon_locked creates the pending entries the
                # consumer's follow-up wait_object parks on.
                if tid not in self.lineage:
                    return "gone"
            elif e["status"] == "pending":
                return "pending"  # reconstruction already in flight
            elif e.get("where") == "inline":
                return "ready"
            if not self._collect_recon_locked(tid, plan, set(), 0):
                return "lost"
        # resubmit upstream-first: _deps_ready gates execution order anyway
        for spec in plan:
            self._submit_task(spec)
        return "reconstructing"

    def _collect_recon_locked(self, tid: str, plan: list, seen: set,
                              depth: int) -> bool:
        """Plan reconstruction of task `tid`'s outputs, recursing into
        missing upstream dependencies. Resets the involved return entries to
        pending (so concurrent reporters dedupe on 'pending')."""
        if tid in seen:
            return True
        if depth > 8:
            return False
        spec = self.lineage.get(tid)
        if spec is None or spec.get("recons_used", 0) >= MAX_RECONSTRUCTIONS:
            return False
        for dep in list(spec.get("deps", ())) + list(spec.get("ref_holds", ())):
            de = self.objects.get(dep)
            missing = (
                de is None
                or (de["status"] == "ready" and de.get("where") == "shm"
                    and not de.get("hosts")))
            if missing and not self._collect_recon_locked(
                    dep[:-5], plan, seen, depth + 1):
                return False
        spec["recons_used"] = spec.get("recons_used", 0) + 1
        seen.add(tid)
        for i in range(spec["num_returns"]):
            roid = f"{tid}r{i:04d}"
            re_ = self.objects.get(roid)
            if re_ is None:
                self.objects[roid] = {"status": "pending", "where": None,
                                      "inline": None, "size": 0}
            elif re_["status"] != "pending":
                self._drop_shm_copies_locked(re_)
                re_.update(status="pending", inline=None)
                re_["hosts"] = set()
                # the re-run will report fresh nested refs; keeping the old
                # 'contained' would make task_done skip taking holds on them
                stale = re_.pop("contained", None)
                if stale:
                    self._sys_hold_locked(stale, -1)
        newspec = {k: v for k, v in spec.items()
                   if k not in ("_paid", "_holds", "_fp_res", "retries_used", "recons_used")}
        # a hard affinity to a dead node would make reconstruction
        # unschedulable forever; the data matters more than the placement
        strat = newspec.get("strategy")
        if strat and strat.get("kind") == "node_affinity":
            node = self.nodes.get(strat.get("node_id"))
            if node is None or not node.alive:
                newspec.pop("strategy", None)
        plan.append(newspec)
        return True

    def _reply_object(self, conn: MsgConnection, rid: int, entry: dict):
        with self.lock:
            locs = self._object_locations_locked(entry)
        try:
            conn.send({
                "rid": rid, "ready": True, "status": entry["status"],
                "where": entry["where"], "inline": entry["inline"], "size": entry["size"],
                "locations": locs,
            })
        except ConnectionClosed:
            pass

    def _wait_object(self, conn: MsgConnection, msg: dict):
        oid = msg["oid"]
        with self.lock:
            entry = self.objects.get(oid)
            if entry is None or entry["status"] == "pending":
                self.object_waiters.setdefault(oid, []).append((conn, msg["rid"]))
                return
            entry["last_access"] = time.monotonic()  # LRU signal for the spiller
        self._reply_object(conn, msg["rid"], entry)

    def _park_relay(self, conn: MsgConnection, msg: dict, *, prefix: str,
                    payload: dict, ttl: float = 30.0) -> None:
        """Forward `payload` (plus a reply token) to msg["wid"] and park the
        requester until the worker's stacks_reply comes back; waiters are
        (conn, rid, wid, parked_at, ttl) — expired by the health loop."""
        with self.lock:
            target = self.workers.get(msg["wid"])
            if target is not None and not target.dead:
                token = f"{prefix}-{msg['rid']}-{id(conn) & 0xffffff}"
                self._tensor_exports[token] = (conn, msg["rid"], msg["wid"],
                                               time.monotonic(), ttl)
            else:
                target = None
        if target is None:
            conn.send({"rid": msg["rid"], "ok": False,
                       "error": "no such live worker"})
            return
        try:
            target.conn.send({**payload, "token": token})
        except ConnectionClosed:
            with self.lock:
                self._tensor_exports.pop(token, None)
            conn.send({"rid": msg["rid"], "ok": False,
                       "error": "worker connection lost"})

    # ------------------------------------------------------------- accounting


    @staticmethod
    def _spec_fp(spec: dict) -> dict:
        """Fixed-point view of spec["resources"], cached on the spec —
        schedulers probe the same pending spec many times per pass, and a
        forgotten fp.fp_dict wrapper at a new call site would compare raw
        floats against integer availability (never fits)."""
        r = spec.get("_fp_res")
        if r is None:
            r = fp.fp_dict(spec.get("resources") or {})
            spec["_fp_res"] = r
        return r

    def _fits_for(self, spec: dict) -> str | None:
        """Pick a node for this spec honoring its scheduling strategy.
        Returns node_id or None if nothing fits right now."""
        res = self._spec_fp(spec)
        strat = spec.get("strategy")
        if strat and strat.get("kind") == "pg":
            pg = self.pgs.get(strat["pg_id"])
            if pg is None or pg.state != "created":
                return None
            idx = strat.get("bundle", -1)
            if idx != -1 and not (0 <= idx < len(pg.bundles)):
                return None  # invalid index: rejected at submit time
            cand = pg.bundles if idx == -1 else [pg.bundles[idx]]
            for b in cand:
                if all(b.available.get(k, 0) >= v for k, v in res.items()):
                    return b.node_id
            return None
        if strat and strat.get("kind") == "node_label":
            hard = strat.get("hard", {})
            cands = [n for n in self.nodes.values() if n.alive
                     and not n.draining
                     and all(n.labels.get(k) == v for k, v in hard.items())]
            return pg_policy.pick_node_hybrid(cands, res, self.local_node_id)
        if strat and strat.get("kind") == "node_affinity":
            n = self.nodes.get(strat["node_id"])
            if (n is not None and n.alive and not n.draining
                    and pg_policy._fits(n.available, res)):
                return n.node_id
            if strat.get("soft"):
                return pg_policy.pick_node_hybrid(list(self.nodes.values()), res, self.local_node_id)
            return None
        return pg_policy.pick_node_hybrid(list(self.nodes.values()), res, self.local_node_id)

    def _acquire_for(self, spec: dict, node_id: str):
        res = self._spec_fp(spec)
        strat = spec.get("strategy")
        if strat and strat.get("kind") == "pg":
            pg = self.pgs[strat["pg_id"]]
            idx = strat.get("bundle", -1)
            cands = list(enumerate(pg.bundles)) if idx == -1 else [(idx, pg.bundles[idx])]
            for i, b in cands:
                if b.node_id == node_id and all(b.available.get(k, 0) >= v for k, v in res.items()):
                    for k, v in res.items():
                        b.available[k] = b.available.get(k, 0) - v
                    spec["_paid"] = {"kind": "bundle", "pg_id": pg.pg_id, "bundle": i,
                                     "node": node_id, "epoch": pg.epoch}
                    return
            raise RuntimeError("bundle vanished between fit-check and acquire")
        node = self.nodes[node_id]
        for k, v in res.items():
            node.available[k] = node.available.get(k, 0) - v
        spec["_paid"] = {"kind": "node", "node": node_id}

    def _release_for(self, spec: dict):
        res = self._spec_fp(spec)
        paid = spec.pop("_paid", None)
        if not res or paid is None:
            return
        if paid["kind"] == "bundle":
            pg = self.pgs.get(paid["pg_id"])
            if (pg is not None and pg.state == "created"
                    and paid.get("epoch") == pg.epoch):
                b = pg.bundles[paid["bundle"]]
                for k, v in res.items():
                    b.available[k] = b.available.get(k, 0) + v
                return
            # PG removed (or unplaced+re-placed under a new epoch) while the
            # task ran: the in-use share was withheld from the original node
            # at removal/unplacement; return it to that node now.
        node = self.nodes.get(paid["node"])
        if node is not None and node.alive:
            for k, v in res.items():
                node.available[k] = node.available.get(k, 0) + v

    # ------------------------------------------------------- direct leases
    # (reference: src/ray/raylet/scheduling/cluster_lease_manager.h:41 lease
    # grant/release; normal_task_submitter.h:81 caller-side lease use)

    def _lease_workers(self, conn: MsgConnection, msg: dict, caller: str | None):
        res = msg.get("resources") or {"CPU": 1.0}
        rh = msg.get("renv_hash", "")
        need = accelerators.chips_required(res)
        prefer = msg.get("prefer_host")
        count = max(1, int(msg.get("count", 1)))
        grants: list[dict] = []
        with self.lock:
            # record the caller's local backlog for the autoscaler demand view
            bkey = (caller, tuple(sorted(res.items())), rh)
            backlog = int(msg.get("backlog", 0))
            if backlog > 0:
                self._direct_backlog[bkey] = (dict(res), backlog, time.monotonic())
            else:
                self._direct_backlog.pop(bkey, None)
            if not self.stopped and caller is not None:
                cands = [w for w in self.workers.values()
                         if w.kind == "worker" and not w.dead and w.idle
                         and w.actor_id is None and w.leased_to is None
                         and len(w.tpu_chips) == need and w.renv_hash == rh
                         and w.direct_addr]
                if prefer:
                    cands.sort(key=lambda w: w.host_id != prefer)
                res_fp = fp.fp_dict(res)
                for w in cands:
                    if len(grants) >= count:
                        break
                    node = self.nodes.get(w.node_id)
                    if node is None or not node.alive or node.draining:
                        continue
                    if not pg_policy._fits(node.available, res_fp):
                        continue
                    lspec = {"resources": dict(res)}
                    self._acquire_for(lspec, w.node_id)
                    self._lease_seq += 1
                    w.idle = False
                    w.leased_to = caller
                    w.lease_spec = lspec
                    w.lease_token = self._lease_seq
                    self._leases_by_holder.setdefault(caller, set()).add(w.wid)
                    grants.append({"wid": w.wid, "addr": w.direct_addr,
                                   "host": w.host_id, "node": w.node_id,
                                   "token": self._lease_seq})
        unmet = count - len(grants)
        if unmet > 0:
            self._spawn_for_lease_demand(res, rh, need, unmet)
        if grants:
            self._observe_sched("lease", "granted", None, n=len(grants))
            self._emit_event(
                _const.EVENT_LEASE_GRANT,
                severity=_const.EVENT_SEVERITY_DEBUG,
                message=f"{len(grants)} worker lease(s) to {caller}",
                caller=caller or "", count=len(grants),
                nodes=sorted({g["node"] for g in grants}))
        try:
            conn.send({"rid": msg["rid"], "leases": grants})
        except ConnectionClosed:
            for g in grants:
                self._release_lease(g["wid"], g["token"])

    def _spawn_for_lease_demand(self, res: dict, rh: str, need: int, n: int):
        """Unmet lease demand scales the pool up, same as queued GCS tasks
        do — the caller's next lease attempt then finds idle workers."""
        spawn_plan: list[tuple[str, list]] = []
        now = time.monotonic()
        with self.lock:
            n_workers = sum(1 for w in self.workers.values()
                            if w.kind == "worker" and not w.dead)
            spawning = sum(len(dq) for dq in self._spawn_pending.values())
            headroom = self.max_workers - n_workers - spawning
            n = min(n, headroom)
            if n <= 0:
                return
            node_id = pg_policy.pick_node_hybrid(
                list(self.nodes.values()), fp.fp_dict(res),
                self.local_node_id)
            if node_id is None:
                return
            node = self.nodes.get(node_id)
            assignments: list = []
            for _ in range(n):
                if need == 0:
                    assignments.append(None)
                    continue
                if (node is None or not node.alive or node.draining
                        or len(node.chip_pool) < need):
                    break
                chips = tuple(node.chip_pool[:need])
                del node.chip_pool[:need]
                assignments.append(chips)
            if not assignments:
                return
            self._spawn_pending[node_id].extend(
                (now, c, rh) for c in assignments)
            host = self.node_hosts.get(node_id, HEAD_HOST)
            agent_conn = self.hosts.get(host, {}).get("conn")
            renv = self.runtime_envs.get(rh) if rh else None
            spawn_plan.append((node_id, assignments, agent_conn, renv))
        for node_id, assignments, agent_conn, renv in spawn_plan:
            if agent_conn is not None:
                try:
                    agent_conn.send({"type": "spawn_workers",
                                     "node_id": node_id,
                                     "assignments": assignments,
                                     "runtime_env": renv})
                except ConnectionClosed:
                    pass
            else:
                self.spawn_worker_cb(len(assignments), node_id, assignments,
                                     renv)

    def _release_lease(self, target: str, token=None, make_idle: bool = True):
        with self.lock:
            w = self.workers.get(target)
            if w is None or w.leased_to is None:
                return
            if token is not None and token != w.lease_token:
                return  # stale release for an already-recycled lease
            holder = w.leased_to
            w.leased_to = None
            w.lease_token = None
            hs = self._leases_by_holder.get(holder)
            if hs is not None:
                hs.discard(target)
            spec, w.lease_spec = w.lease_spec, None
            if spec is not None:
                self._release_for(spec)
            if not w.dead and make_idle:
                w.idle = True
        self._emit_event(_const.EVENT_LEASE_RELEASE,
                         severity=_const.EVENT_SEVERITY_DEBUG,
                         message=f"lease on {target} released by {holder}",
                         worker=target, holder=holder)
        self._schedule()

    def _convert_cross_lang_done(self, msg: dict) -> None:
        """A JSON-codec (cross-language) worker reports plain JSON result
        values; Python consumers unpickle inline blobs, so re-encode each
        value (or the error) here. Mutates msg into the standard
        task_done shape."""
        import ray_tpu._private.serialization as ser
        from ray_tpu.exceptions import RayTpuError

        err = msg.get("error")
        results = []
        for res in msg.get("results") or ():
            oid, where, value = res[0], res[1], res[2]
            if err is not None:
                blob = ser.dumps(RayTpuError(
                    f"cross-language task failed: {err}"))
            else:
                blob = ser.dumps(value)
            results.append([oid, where, blob, len(blob)])
        msg["results"] = results

    def _fail_orphaned_stubs(self, oids) -> None:
        """Error pending stubs whose promised publisher is gone (caller
        holds no lock)."""
        import ray_tpu._private.serialization as ser
        from ray_tpu.exceptions import OwnerDiedError

        blob = ser.dumps(OwnerDiedError(
            "the process owning this object died before publishing it"))
        for oid in oids:
            self._on_object_ready(oid, where="inline", inline=blob,
                                  size=len(blob), is_error=True,
                                  only_if_pending=True)

    def _host_view_for(self, node_id: str) -> dict | None:
        """Latest resource-view delta of the host backing a node (caller
        holds the lock). Views older than 3 intervals are served with a
        stale flag rather than dropped — a wedged agent's LAST view is
        still diagnostic."""
        host = self.node_hosts.get(node_id, HEAD_HOST)
        view = (self.hosts.get(host) or {}).get("view")
        if not view:
            return None
        out = dict(view)
        age = time.monotonic() - out.pop("ts")
        out["age_s"] = round(age, 1)
        # instance() (not get()) — this runs per node under the GCS lock
        interval = RayConfig.instance().resource_view_interval_s
        out["stale"] = age > 3 * max(0.1, interval)
        return out

    def _pinned_fn_keys_locked(self) -> set:
        """fn: store keys that MUST survive eviction: referenced by a
        pending/running spec (the executor fetches the blob at dispatch) or
        by retained lineage (reconstruction resubmits the spec verbatim).
        Caller holds the lock.

        The scan is O(pending + running + lineage), so the result is cached
        for a few seconds: dynamic-closure floods hit the eviction path on
        EVERY overflowing put, and an uncached scan there would undo the
        sharded-queue submit scaling. Staleness is safe because every key
        referenced in the cache window is also recency-protected — uploads
        and existence probes stamp _fn_access, and eviction spares keys
        touched within the (much longer) 300s freshness window."""
        now = time.monotonic()
        cached = self._pinned_fn_cache
        if cached is not None and now - cached[0] < 5.0:
            return cached[1]
        pinned: set = set()

        def _note(spec):
            sha = spec.get("func_sha")
            if sha:
                pinned.add("fn:" + sha)

        for s in self.pending_tasks:
            _note(s)
        for w in self.workers.values():
            for s in w.running_tasks.values():
                _note(s)
        for a in self.actors.values():
            for s in a.queue:
                _note(s)
        for s in self.lineage.values():
            _note(s)
        self._pinned_fn_cache = (now, pinned)
        return pinned

    def _retain_lineage_locked(self, spec: dict) -> list[str]:
        """Retain a task spec for lineage reconstruction of its outputs,
        under the bounded budget (reference: lineage eviction). A
        reconstruction resubmit keeps its spent budget. Returns oids freed
        by eviction; caller holds the lock."""
        prev_lin = self.lineage.get(spec["task_id"])
        lin = {k: v for k, v in spec.items()
               if k not in ("_paid", "_holds", "_fp_res", "retries_used")}
        if prev_lin is not None:
            lin["recons_used"] = prev_lin.get("recons_used", 0)
        self.lineage[spec["task_id"]] = lin
        evicted: list[str] = []
        # deep-queue fast path: if the last walk found every candidate still
        # queued/running (a 1M-task queue keeps the oldest lineage pinned),
        # repeating the walk on each of the next million submits is O(K)
        # futile probes per submit. The verdict only changes when a task
        # completes, so stay stalled until _on_task_done clears the flag.
        if getattr(self, "_lineage_evict_stalled", False):
            return evicted
        if len(self.lineage) > MAX_LINEAGE:
            # evict oldest-first, but never a task that is still
            # queued/running — dropping one would free its pinned
            # args blob under it and hang the dispatch. Queued-ness is an
            # O(1) multiset probe; the candidate walk is BOUNDED so a deep
            # queue (every lineage entry still pending) costs O(K) per
            # submit, not O(lineage) — the budget is soft and the excess
            # drains as soon as tasks start completing
            running: set = set()
            for w_ in self.workers.values():
                running.update(w_.running_tasks.keys())
            candidates = list(itertools.islice(self.lineage, 64))
            for tid in candidates:
                if len(self.lineage) <= MAX_LINEAGE:
                    break
                if (tid == spec["task_id"] or tid in running
                        or self.pending_tasks.is_queued(tid)):
                    continue
                evicted.extend(self._drop_lineage_locked(tid))
            if not evicted and len(self.lineage) > MAX_LINEAGE:
                self._lineage_evict_stalled = True
        return evicted

    # ----------------------------------------------------------------- tasks

    def _invalid_strategy_reason(self, strat: dict | None) -> str | None:
        """Reject structurally-invalid strategies at submit time (caller holds lock)."""
        if not strat or strat.get("kind") != "pg":
            return None
        pg = self.pgs.get(strat.get("pg_id"))
        if pg is None:
            return f"no such placement group {strat.get('pg_id')!r}"
        if pg.state == "removed":
            return "placement group has been removed"
        idx = strat.get("bundle", -1)
        if idx != -1 and not (0 <= idx < len(pg.bundles)):
            return (f"placement_group_bundle_index {idx} out of range "
                    f"for {len(pg.bundles)} bundles")
        return None

    def _submit_task(self, spec: dict):
        with self.lock:
            if spec.get("renv_hash"):
                self.runtime_envs[spec["renv_hash"]] = spec.get("runtime_env") or {}
            if spec["num_returns"] == "streaming":
                self.streams[spec["task_id"]] = {
                    "items": [], "done": False, "error": None,
                    "consumed": 0, "producer": None, "waiters": []}
            else:
                for i in range(spec["num_returns"]):
                    oid = f"{spec['task_id']}r{i:04d}"
                    e = self.objects.setdefault(oid, {"status": "pending", "where": None, "inline": None, "size": 0})
                    # the GCS path now owns producing this value; a stale
                    # will_publish promise (direct spec redirected here)
                    # must not let the old owner's death error the stub
                    pw = e.pop("pub_wid", None)
                    if pw is not None:
                        s = self._pub_promises.get(pw)
                        if s is not None:
                            s.discard(oid)
                            if not s:
                                self._pub_promises.pop(pw, None)
            reason = self._invalid_strategy_reason(spec.get("strategy"))
            if reason is None:
                # hold every object this task needs (args + refs nested in
                # args) until it completes, so a caller dropping its handles
                # mid-flight can't free them under the task
                holds = list(spec.get("deps", ())) + list(spec.get("ref_holds", ()))
                spec["_holds"] = holds
                self._sys_hold_locked(holds, +1)
                evicted: list[str] = []
                if spec["kind"] == "task" and isinstance(spec["num_returns"], int):
                    evicted = self._retain_lineage_locked(spec)
                spec["_enq_ts"] = time.monotonic()
                self.pending_tasks.append(spec)
            self.task_counter["submitted"] += 1
        if reason is not None:
            self._fail_task_objects(spec, reason)
            return
        if evicted:
            self._free_objects(evicted)
        self._schedule()

    def _deps_ready(self, spec: dict) -> bool:
        for dep in spec.get("deps", ()):
            e = self.objects.get(dep)
            if e is None or e["status"] == "pending":
                return False
        return True

    def _schedule(self):
        """Dispatch whatever can run; request worker scale-up for the rest."""
        to_send: list[tuple[MsgConnection, dict]] = []
        want_spawn: collections.Counter = collections.Counter()  # (node, n_chips) → demand
        revokes: list[tuple[MsgConnection, str]] = []
        with self.lock:
            if self.stopped:
                return
            self._try_place_pgs_locked()
            idle_by_node: dict[str, list[_Worker]] = collections.defaultdict(list)
            n_alive = 0
            for w in self.workers.values():
                if w.kind == "worker" and not w.dead:
                    n_alive += 1
                    if w.idle and w.actor_id is None:
                        idle_by_node[w.node_id].append(w)
            # purge timed-out spawn requests FIRST: a silently failed spawn
            # must free its headroom before the feasibility decision below,
            # or it suppresses both scanning and respawn until an unrelated
            # event
            now = time.monotonic()
            for node_id_, dq in self._spawn_pending.items():
                while dq:
                    ts_, chips_, rh_ = dq[0]
                    # pip runtime envs build a venv inside the worker boot:
                    # give them the long budget too
                    pip_env = bool(rh_ and (self.runtime_envs.get(rh_)
                                            or {}).get("pip"))
                    limit_ = (PIP_SPAWN_TIMEOUT_S if pip_env
                              else CHIP_SPAWN_TIMEOUT_S if chips_
                              else SPAWN_TIMEOUT_S)
                    if now - ts_ <= limit_:
                        break
                    dq.popleft()  # spawn presumed failed; allow retry
                    if chips_:
                        node_ = self.nodes.get(node_id_)
                        if node_ is not None and node_.alive:
                            node_.chip_pool.extend(chips_)
            # scalability early-exit (reference envelope: 1M queued tasks on
            # a node — BASELINE.md): when nothing can possibly dispatch (no
            # idle worker) and nothing can spawn (no headroom), scanning the
            # whole pending queue per event would make submission O(queue²).
            # Actor METHOD dispatch doesn't need idle workers, so that loop
            # still runs below.
            spawning_now = sum(len(dq) for dq in self._spawn_pending.values())
            can_place = (any(idle_by_node.values())
                         or self.max_workers - n_alive - spawning_now > 0)

            dispatched_any = False
            # why the most recent dispatch() returned False: "deps" (spec-
            # specific — a later spec in the same shard may still run) vs
            # "capacity" (no fitting node / no matching idle worker — for a
            # uniform shard this verdict covers every other spec too)
            fail_reason = ""

            def dispatch(spec) -> bool:
                nonlocal dispatched_any, fail_reason
                fail_reason = "capacity"
                lang = spec.get("lang", "py")
                need = accelerators.chips_required(spec.get("resources", {}))
                rh = spec.get("renv_hash", "")
                if lang != "py":
                    # cross-language workers self-join on whatever node
                    # their operator chose: place the task WHERE such a
                    # worker is, not where resources look emptiest (the
                    # GCS cannot spawn one, so demand registration is
                    # pointless). Prefer a worker that registered the
                    # function by name.
                    if not self._deps_ready(spec):
                        fail_reason = "deps"
                        return False
                    fname = spec.get("func_name")
                    cands = [x for pool in idle_by_node.values()
                             for x in pool
                             if x.language == lang
                             and len(x.tpu_chips) == need
                             and x.renv_hash == rh
                             and pg_policy._fits(
                                 self.nodes[x.node_id].available,
                                 self._spec_fp(spec))]
                    if not cands:
                        return False
                    w = next((x for x in cands
                              if not x.functions or fname in x.functions),
                             cands[0])
                    node_id = w.node_id
                    pool = idle_by_node.get(node_id, [])
                else:
                    node_id = self._fits_for(spec)
                    if node_id is None:
                        return False
                    if not self._deps_ready(spec):
                        fail_reason = "deps"
                        return False
                    # whole-chip TPU specs need a worker spawned with
                    # exactly that many chips visible; CPU specs need a
                    # chipless worker (a chip worker must stay free for
                    # TPU demand)
                    pool = idle_by_node.get(node_id, [])
                    w = next((x for x in pool if len(x.tpu_chips) == need
                              and x.renv_hash == rh and x.language == lang),
                             None)
                if w is None:
                    fail_reason = "capacity_demand"  # spawn demand registered
                    want_spawn[(node_id, need, rh)] += 1
                    return False
                pool.remove(w)
                self._acquire_for(spec, node_id)
                w.idle = False
                spec["_ts"] = time.monotonic()
                w.running_tasks[spec["task_id"]] = spec
                wait = spec["_ts"] - spec.get("_enq_ts", spec["_ts"])
                if spec["kind"] == "actor_create":
                    w.actor_id = spec["actor_id"]
                    actor = self.actors[spec["actor_id"]]
                    actor.worker = w.wid
                    self._observe_sched("actor", "dispatched", wait)
                    self._trace_decision(spec["actor_id"], "dispatched",
                                         node=node_id, worker=w.wid,
                                         queue_wait_s=round(wait, 6))
                else:
                    self._observe_sched("task", "dispatched", wait)
                to_send.append((w.conn, {"type": "exec", "spec": spec}))
                self.pending_tasks.note_consumed(spec["task_id"])
                dispatched_any = True
                return True

            if can_place:
                # bounded scan: mostly-FIFO dispatch that gives up after a
                # run of consecutive non-dispatchable specs — per-event work
                # stays O(idle + K) instead of O(queue), which is what keeps
                # deep queues (reference envelope: 1M pending) from turning
                # every completion into a full rescan. K>1 so heterogeneous
                # resource shapes behind a stuck head still make progress.
                K = 64

                # liveness vs bound: while idle workers remain, scan deeper
                # (up to K_IDLE) so dispatchable specs behind stuck heads are
                # reached; if we STILL stop early with idle workers left, the
                # scanned misses rotate to the tail so successive events make
                # eventual progress through the whole queue instead of
                # re-hitting the same head forever. O(1) idle tracking: a
                # counter decremented where dispatch consumes a worker.
                K_IDLE = 1024
                idle_left = sum(len(v) for v in idle_by_node.values())

                def keep_scanning(misses: int) -> bool:
                    if misses < K:
                        return True
                    return idle_left > 0 and misses < K_IDLE

                def scan(queue: collections.deque, skip=None,
                         uniform: bool = False) -> str:
                    """Dispatch from `queue`; returns the fail_reason it
                    stopped on for a UNIFORM queue's capacity miss (every
                    remaining spec shares the failing spec's resource shape,
                    so one miss is a verdict for the whole shard — the
                    caller then registers bulk spawn demand instead of
                    probing spec by spec), else ""."""
                    nonlocal idle_left
                    still = collections.deque()
                    misses = 0
                    cap_stop = ""
                    while queue and keep_scanning(misses):
                        spec = queue.popleft()
                        if skip is not None and skip(spec):
                            continue
                        if dispatch(spec):
                            idle_left -= 1  # creations/tasks consume a worker
                            misses = 0
                        else:
                            still.append(spec)
                            misses += 1
                            if uniform and fail_reason.startswith("capacity"):
                                cap_stop = fail_reason
                                break
                    if still and queue and idle_left > 0 and not cap_stop:
                        queue.extend(still)  # rotate: different specs next event
                    else:
                        queue.extendleft(reversed(still))
                    return cap_stop

                # actor creations first (they pin workers)
                def _dead_actor(spec):
                    actor = self.actors.get(spec["actor_id"])
                    return actor is None or actor.state == "dead"

                scan(self.pending_actor_creations, skip=_dead_actor)
                # strategy specs: placement varies per spec, scan them all
                scan(self.pending_tasks.misc)
                # uniform shards: one feasibility probe covers the shard
                for key, dq in list(self.pending_tasks.shards.items()):
                    if not dq:
                        del self.pending_tasks.shards[key]
                        continue
                    res = dq[0].get("resources") or {}
                    rh, lang = key[1], key[2]
                    need = accelerators.chips_required(res)
                    probe_registered = 0
                    if any(len(x.tpu_chips) == need and x.renv_hash == rh
                           and x.language == lang
                           for pool in idle_by_node.values() for x in pool):
                        stop = scan(dq, uniform=True)
                        if not stop:
                            continue
                        # capacity-stopped mid-scan: the idle workers are
                        # consumed/mismatched, so fall through to bulk
                        # demand registration exactly as if none had matched.
                        # The probing dispatch may itself have registered +1
                        # for the spec now back at the queue head — don't
                        # count it twice below.
                        probe_registered = 1 if stop == "capacity_demand" else 0
                    if lang != "py":
                        continue  # cross-language workers self-join: no spawn
                    # no matching idle worker anywhere: nothing in this
                    # shard can dispatch this pass. Register spawn demand
                    # for the RUNNABLE prefix only (a dep-blocked shard must
                    # not trigger spawns/reclaims/revocations for tasks that
                    # couldn't run anyway) — bounded probe, O(K) per shard
                    node_id = pg_policy.pick_node_hybrid(
                        list(self.nodes.values()), fp.fp_dict(res),
                        self.local_node_id)
                    if node_id is not None:
                        runnable = sum(1 for s in itertools.islice(dq, 64)
                                       if self._deps_ready(s))
                        runnable -= probe_registered
                        if runnable > 0:
                            want_spawn[(node_id, need, rh)] += runnable

            # warm-pool floor: replenish idle no-env CPU workers consumed
            # by dispatch/leases so the next cold task is a dispatch, not a
            # process fork + imports (reference: worker_pool.h:280
            # prestarted pool). Deficits are NOT merged into want_spawn:
            # real demand may retire mismatched workers and revoke leases
            # to make room, but background replenishment must only ever use
            # LEFTOVER headroom (see the post-scale-up block below).
            warm_needs: dict[str, int] = {}
            if self.warm_pool_size > 0:
                for node_id_w, node_w in self.nodes.items():
                    if not node_w.alive:
                        continue
                    idle_plain = sum(
                        1 for x in idle_by_node.get(node_id_w, ())
                        if not x.tpu_chips and x.renv_hash == ""
                        and x.language == "py")
                    if self.warm_pool_size > idle_plain:
                        warm_needs[node_id_w] = self.warm_pool_size - idle_plain

            # pending work that couldn't dispatch while leases hold the
            # resources it needs: revoke exactly those leases (reference:
            # leases are returned under cluster pressure / spillback)
            if ((self.pending_tasks or self.pending_actor_creations)
                    and not dispatched_any):
                for lw in self.workers.values():
                    if (lw.kind == "worker" and not lw.dead
                            and lw.leased_to is not None
                            and self._lease_would_help_locked(lw)):
                        holder = self.workers.get(lw.leased_to)
                        if holder is not None and not holder.dead:
                            revokes.append((holder.conn, lw.wid))

            # actor method calls (up to max_concurrency in flight per actor;
            # group-declared methods dispatch through their own lane so a
            # control call — e.g. a serve health probe — is never stuck
            # behind a saturated default queue)
            for actor in self.actors.values():
                if actor.state != "alive" or not actor.queue:
                    continue
                w = self.workers.get(actor.worker)
                if w is None or w.dead:
                    continue
                if actor.group_queued > 0:
                    self._dispatch_actor_grouped_locked(actor, w, to_send)
                    continue
                # fast path: nothing bound for a group lane is queued, so
                # heads are all default-pool specs — FIFO up to the default
                # cap (total minus reserved group slots)
                base_cap = actor.max_concurrency - sum(actor.groups.values())
                while (actor.queue
                       and actor.in_flight
                       - sum(actor.group_in_flight.values()) < base_cap):
                    spec = actor.queue.popleft()
                    actor.in_flight += 1
                    w.running_tasks[spec["task_id"]] = spec
                    to_send.append((w.conn, {"type": "exec", "spec": spec}))

            # scale-up: runnable-if-only-there-were-workers, per (node, chips)
            # (stale spawn requests were purged at the top of this pass)
            now = time.monotonic()
            n_workers = sum(1 for w in self.workers.values() if w.kind == "worker" and not w.dead)
            spawning_total = sum(len(dq) for dq in self._spawn_pending.values())
            spawn_plan: list[tuple[str, list]] = []  # node_id, [chips|None per worker]
            reclaim: list[_Worker] = []
            headroom = self.max_workers - n_workers - spawning_total
            for (node_id, need, rh), demand in want_spawn.items():
                spawning_here = sum(
                    1 for _, c, prh in self._spawn_pending[node_id]
                    if len(c or ()) == need and prh == rh)
                want = demand - spawning_here
                if want <= 0:
                    continue
                node = self.nodes.get(node_id)
                # free headroom and/or chips by retiring idle workers whose
                # binding can't serve this demand (a process can't change
                # its visible chips after jax backend init)
                short_headroom = want - headroom
                short_chips = (need > 0 and node is not None
                               and len(node.chip_pool) < need * want)
                if short_headroom > 0 or short_chips:
                    got = self._reclaim_mismatched_idle_locked(
                        node_id, need, max(short_headroom, want), rh)
                    headroom += len(got)
                    reclaim.extend(got)
                n = max(0, min(want, headroom))
                if n < want:
                    # demand this pass can't spawn for: ask lease holders to
                    # hand matching leased workers back (reference: leases are
                    # revoked/spilled back under cluster pressure)
                    needed = want - n
                    for lw in self.workers.values():
                        if needed <= 0:
                            break
                        if (lw.kind == "worker" and not lw.dead
                                and lw.leased_to is not None
                                and len(lw.tpu_chips) == need
                                and lw.renv_hash == rh):
                            holder = self.workers.get(lw.leased_to)
                            if holder is not None and not holder.dead:
                                revokes.append((holder.conn, lw.wid))
                                needed -= 1
                if n <= 0:
                    continue
                assignments: list = []
                for _ in range(n):
                    if need == 0:
                        assignments.append(None)
                        continue
                    if node is None or not node.alive or len(node.chip_pool) < need:
                        break
                    chips = tuple(node.chip_pool[:need])
                    del node.chip_pool[:need]
                    assignments.append(chips)
                if assignments:
                    headroom -= len(assignments)
                    self._spawn_pending[node_id].extend(
                        (now, c, rh) for c in assignments)
                    spawn_plan.append((node_id, assignments, rh))
            # warm-pool replenishment: strictly leftover headroom, shared
            # across nodes, never reclaims or revokes anything
            for node_id_w, deficit in warm_needs.items():
                if headroom <= 0:
                    break
                spawning_plain = sum(
                    1 for _, c_, rh_ in self._spawn_pending[node_id_w]
                    if not c_ and rh_ == "")
                n = min(deficit - spawning_plain, headroom)
                if n <= 0:
                    continue
                headroom -= n
                self._spawn_pending[node_id_w].extend(
                    (now, None, "") for _ in range(n))
                spawn_plan.append((node_id_w, [None] * n, ""))
            agent_sends = []
            for node_id, assignments, rh in spawn_plan:
                host = self.node_hosts.get(node_id, HEAD_HOST)
                agent_conn = self.hosts.get(host, {}).get("conn")
                if agent_conn is not None:
                    agent_sends.append(
                        (agent_conn, node_id, assignments,
                         self.runtime_envs.get(rh) if rh else None))
            spawn_plan = [(nid, a, rh) for nid, a, rh in spawn_plan
                          if self.hosts.get(self.node_hosts.get(nid, HEAD_HOST), {}).get("conn") is None]

        for conn, msg in to_send:
            try:
                conn.send(msg)
            except ConnectionClosed:
                pass
        for w in reclaim:
            try:
                w.conn.send({"type": "exit"})
            except ConnectionClosed:
                pass
        for hconn, lw in revokes:
            try:
                hconn.send({"type": "lease_revoke", "wid": lw})
            except ConnectionClosed:
                pass
        for agent_conn, node_id, assignments, renv in agent_sends:
            try:
                agent_conn.send({"type": "spawn_workers", "node_id": node_id,
                                 "assignments": assignments,
                                 "runtime_env": renv})
            except ConnectionClosed:
                pass
        for node_id, assignments, rh in spawn_plan:
            self.spawn_worker_cb(len(assignments), node_id, assignments,
                                 self.runtime_envs.get(rh) if rh else None)

    def _dispatch_actor_grouped_locked(self, actor: _Actor, w: _Worker,
                                       to_send: list) -> None:
        """Dispatch an actor's queue with per-lane caps: group-declared
        methods fill their group's slots regardless of position (a probe
        queued behind 50 data requests still dispatches), default specs
        fill the default pool FIFO. Called only when at least one queued
        spec is bound for a group lane (group_queued > 0)."""
        base_cap = actor.max_concurrency - sum(actor.groups.values())
        default_in_flight = actor.in_flight - sum(
            actor.group_in_flight.values())
        group_left = actor.group_queued  # group-bound specs not yet visited
        remaining: collections.deque[dict] = collections.deque()
        while actor.queue:
            if default_in_flight >= base_cap and (
                    group_left <= 0
                    or all(actor.group_in_flight.get(g, 0) >= lim
                           for g, lim in actor.groups.items())):
                # nothing further can dispatch: the default lane is full and
                # either every group-bound spec has been visited or every
                # group lane is full — don't churn the (possibly deep)
                # default backlog
                remaining.extend(actor.queue)
                actor.queue.clear()
                break
            spec = actor.queue.popleft()
            g = actor.method_groups.get(spec.get("method") or "")
            if g is not None:
                group_left -= 1
                if actor.group_in_flight.get(g, 0) >= actor.groups[g]:
                    remaining.append(spec)
                    continue
                actor.group_in_flight[g] = actor.group_in_flight.get(g, 0) + 1
                actor.group_queued -= 1
                spec["_cgroup"] = g  # for the done/death decrement
            else:
                if default_in_flight >= base_cap:
                    remaining.append(spec)
                    continue
                default_in_flight += 1
            actor.in_flight += 1
            w.running_tasks[spec["task_id"]] = spec
            to_send.append((w.conn, {"type": "exec", "spec": spec}))
        actor.queue = remaining

    def _lease_would_help_locked(self, lw: _Worker) -> bool:
        """Would returning this worker's lease make any head-of-queue
        pending spec resource-feasible on its node? Only specs that are
        dep-ready AND actually resource-blocked count — revoking for work
        that is waiting on something else would just churn the lease pool."""
        node = self.nodes.get(lw.node_id)
        if node is None or not node.alive:
            return False
        avail0 = node.available
        avail = dict(avail0)
        for k, v in fp.fp_dict(
                (lw.lease_spec or {}).get("resources", {})).items():
            avail[k] = avail.get(k, 0) + v
        for spec in itertools.islice(
                itertools.chain(self.pending_actor_creations,
                                self.pending_tasks), 32):
            res = self._spec_fp(spec)
            if not self._deps_ready(spec):
                continue
            if all(avail0.get(k, 0) >= v for k, v in res.items()):
                continue  # resources already free: blocked on workers, not us
            if all(avail.get(k, 0) >= v for k, v in res.items()):
                return True
        return False

    def _reclaim_mismatched_idle_locked(self, node_id: str, need: int,
                                        max_count: int,
                                        renv_hash: str = "") -> list[_Worker]:
        """Retire idle workers on a node whose chip binding differs from the
        wanted one (chip workers blocking CPU demand, or CPU/odd-size chip
        workers blocking chip demand). Runs after all dispatch for this
        round, so anything still idle here failed to match current demand.
        Caller sends the exit messages."""
        out: list[_Worker] = []
        node = self.nodes.get(node_id)
        for w in self.workers.values():
            if len(out) >= max_count:
                break
            if (w.kind == "worker" and not w.dead and w.idle
                    and w.actor_id is None and w.node_id == node_id
                    and w.language == "py"  # self-joined cpp workers are
                    # not respawnable: never retire them for headroom
                    and (len(w.tpu_chips) != need
                         or w.renv_hash != renv_hash)):
                w.dead = True
                if w.tpu_chips and node is not None and node.alive:
                    node.chip_pool.extend(w.tpu_chips)
                out.append(w)
        return out

    def _on_task_done(self, msg: dict):
        wid = msg["wid"]
        with self.lock:
            # a completion can unpin the oldest lineage entries — re-arm the
            # bounded eviction walk (see _retain_lineage_locked)
            self._lineage_evict_stalled = False
            w = self.workers.get(wid)
            spec = msg["spec"]
            # prefer the GCS-side spec: it carries the _paid accounting tag the
            # worker's lite echo doesn't (the worker never sees reservations)
            if w is not None:
                # the top-level task_id is authoritative (direct dispatch
                # keys on it too); the lite spec echo is the fallback for
                # cross-language peers that omit it
                gcs_spec = w.running_tasks.pop(
                    msg.get("task_id") or spec.get("task_id"), None)
                if gcs_spec is not None:
                    spec = gcs_spec
            kind = spec["kind"]
            error = msg.get("error")
            if kind == "actor_create":
                actor = self.actors.get(spec["actor_id"])
                rtt = time.monotonic() - spec.get("_ts", time.monotonic())
                if error is None:
                    if actor is not None:
                        actor.state = "alive"
                        self._observe_sched("actor", "created", rtt)
                        self._trace_decision(actor.aid, "created",
                                             lease_rtt_s=round(rtt, 6))
                        self._emit_event(
                            _const.EVENT_ACTOR_ALIVE,
                            node=w.node_id if w is not None else "",
                            message=f"actor {actor.name or actor.aid} alive "
                                    f"on worker {wid}",
                            actor_id=actor.aid, name=actor.name, worker=wid,
                            num_restarts=actor.num_restarts)
                        self.publish("actor_state",
                                     {"actor_id": actor.aid, "state": "alive"})
                        waiters, actor.waiters = actor.waiters, []
                        for conn, rid in waiters:
                            try:
                                conn.send({"rid": rid, "ok": True})
                            except ConnectionClosed:
                                pass
                        if actor.kill_requested and w is not None and not w.dead:
                            try:
                                w.conn.send({"type": "kill_actor", "aid": actor.aid})
                            except ConnectionClosed:
                                pass
                else:
                    # creation failed → actor dead, release worker
                    if actor is not None:
                        actor.state = "dead"
                        self._observe_sched("actor", "failed", rtt)
                        self._trace_decision(actor.aid, "failed", error=error)
                        self._emit_event(
                            _const.EVENT_ACTOR_DEAD,
                            severity=_const.EVENT_SEVERITY_ERROR,
                            node=w.node_id if w is not None else "",
                            message=f"actor {actor.name or actor.aid} "
                                    f"creation failed: {error}",
                            actor_id=actor.aid, name=actor.name,
                            death_reason=f"creation failed: {error}")
                        self._unpersist_actor(actor.aid)
                        self.publish("actor_state",
                                     {"actor_id": actor.aid, "state": "dead"})
                        for conn, rid in actor.waiters:
                            try:
                                conn.send({"rid": rid, "ok": False, "error": error})
                            except ConnectionClosed:
                                pass
                        actor.waiters = []
                    if w is not None:
                        w.actor_id = None
                        w.idle = True
                    self._release_for(spec)
            else:
                if kind == "actor_task":
                    actor = self.actors.get(spec["actor_id"])
                    if actor is not None:
                        actor.in_flight = max(0, actor.in_flight - 1)
                        g = spec.get("_cgroup")
                        if g:
                            actor.group_in_flight[g] = max(
                                0, actor.group_in_flight.get(g, 0) - 1)
                else:
                    if w is not None:
                        w.idle = True
                    self._release_for(spec)
            self.task_counter["finished" if error is None else "failed"] += 1
            self.task_events.append({
                "task_id": spec.get("task_id"), "kind": kind, "name": spec.get("name"),
                "worker": wid, "error": error, "ts": time.time(),
            })
            if error is not None:
                # error channel (reference: GCS pubsub error_info channel
                # surfaced by drivers' error pollers)
                self.publish("errors", {
                    "task_id": spec.get("task_id"), "kind": kind,
                    "name": spec.get("name"), "worker": wid,
                    "error": error, "ts": time.time()})

            # the task is over: release its holds on args/nested refs
            free_now = self._sys_hold_locked(spec.pop("_holds", ()), -1)
            if kind == "actor_task":
                free_now.extend(self._unpin_args_locked(spec))
            if kind == "actor_create" and error is not None:
                # creation failed permanently: creation-arg holds + args blob
                free_now.extend(self._actor_dead_cleanup_locked(spec))

            # record results, with the producing host as the shm location so
            # cross-host consumers know where to pull from
            host = w.host_id if w is not None else HEAD_HOST
            contained_map = msg.get("contained") or {}
            dev_map = msg.get("device_tensors") or {}
            if not isinstance(dev_map, dict):
                # legacy flat-list wire form: attribute to every result
                dev_map = ({f"{spec['task_id']}r{i:04d}": list(dev_map)
                            for i in range(spec["num_returns"])}
                           if isinstance(spec["num_returns"], int) else {})
            any_shm = False
            for res in msg.get("results", ()):
                oid, where, inline, size = res[:4]
                # 5th element: actual tier ("spill" = landed on disk because
                # tmpfs was full — a readable host copy, but not tmpfs bytes)
                tier = res[4] if len(res) > 4 else "shm"
                prev = self.objects.get(oid)
                if prev is not None:
                    self._drop_shm_copies_locked(prev)
                entry = self.objects[oid] = {
                    **(prev or {}),
                    "status": "error" if error is not None else "ready",
                    "where": where, "inline": inline, "size": size,
                    "hosts": {host} if where == "shm" else set(),
                }
                if where == "shm":
                    entry["shm_live"] = set()
                    if tier == "shm":
                        self._note_shm_copy_locked(entry, host)
                        any_shm = True
                refs = contained_map.get(oid)
                if refs and "contained" not in (prev or {}):
                    entry["contained"] = list(refs)
                    self._sys_hold_locked(refs, +1)
                if dev_map.get(oid):
                    # RDT: THIS result carries markers into wid's HBM
                    # registry; freeing this object frees exactly those
                    # entries — other results' tensors stay live
                    entry["device_tensors"] = (wid, list(dev_map[oid]))
                for conn, rid in self.object_waiters.pop(oid, []):
                    self._reply_object(conn, rid, entry)
                if self._freeable_locked(oid, entry):
                    free_now.append(oid)
        if free_now:
            self._free_objects(free_now)
        if any_shm:
            self._maybe_spill(host)
        self._schedule()

    # ---------------------------------------------------------------- actors

    def _create_actor(self, spec: dict, _persist: bool = True) -> str | None:
        with self.lock:
            reason = self._invalid_strategy_reason(spec.get("strategy"))
            if reason is not None:
                return reason
            if spec.get("renv_hash"):
                self.runtime_envs[spec["renv_hash"]] = spec.get("runtime_env") or {}
            aid = spec["actor_id"]
            actor = _Actor(aid, spec)
            if actor.name:
                ns = spec.get("namespace") or "default"
                key = (ns, actor.name)
                existing = self.named_actors.get(key)
                if existing is not None and self.actors[existing].state != "dead":
                    return (f"an actor named {actor.name!r} already exists "
                            f"in namespace {ns!r}")
                self.named_actors[key] = aid
            self.actors[aid] = actor
            # creation args stay holdable for the actor's whole life (it may
            # be restarted from the same spec)
            holds = list(spec.get("deps", ())) + list(spec.get("ref_holds", ()))
            spec["_actor_holds"] = holds
            self._sys_hold_locked(holds, +1)
            spec["_enq_ts"] = time.monotonic()
            self.pending_actor_creations.append(spec)
            self._trace_enqueue(aid, "actor")
        self._emit_event(
            _const.EVENT_ACTOR_PENDING,
            message=f"actor {actor.name or aid} "
                    f"({spec.get('class_name')}) queued for placement",
            actor_id=aid, name=actor.name, actor_class=spec.get("class_name"))
        if _persist and self.storage is not None:
            clean = {k: v for k, v in spec.items()
                     if k not in ("_actor_holds", "_paid", "_fp_res",
                                  "_enq_ts")}
            self.storage.put("actors", aid, clean)
        self._schedule()
        return None

    def _submit_actor_task(self, spec: dict) -> tuple[bool, str | None]:
        with self.lock:
            actor = self.actors.get(spec["actor_id"])
            if actor is None or actor.state == "dead":
                return False, "ActorDiedError"
            if spec["num_returns"] == "streaming":
                self.streams[spec["task_id"]] = {
                    "items": [], "done": False, "error": None,
                    "consumed": 0, "producer": None, "waiters": []}
            else:
                for i in range(spec["num_returns"]):
                    oid = f"{spec['task_id']}r{i:04d}"
                    e = self.objects.setdefault(oid, {"status": "pending", "where": None, "inline": None, "size": 0})
                    # the GCS path now owns producing this value; a stale
                    # will_publish promise (direct spec redirected here)
                    # must not let the old owner's death error the stub
                    pw = e.pop("pub_wid", None)
                    if pw is not None:
                        s = self._pub_promises.get(pw)
                        if s is not None:
                            s.discard(oid)
                            if not s:
                                self._pub_promises.pop(pw, None)
            holds = list(spec.get("deps", ())) + list(spec.get("ref_holds", ()))
            spec["_holds"] = holds
            self._sys_hold_locked(holds, +1)
            actor.queue.append(spec)
            if actor.method_groups.get(spec.get("method") or "") is not None:
                actor.group_queued += 1
        self._schedule()
        return True, None

    def _wait_actor_ready(self, conn: MsgConnection, msg: dict):
        with self.lock:
            actor = self.actors.get(msg["aid"])
            if actor is None:
                pass
            elif actor.state == "alive":
                conn.send({"rid": msg["rid"], "ok": True})
                return
            elif actor.state in ("pending", "restarting"):
                actor.waiters.append((conn, msg["rid"]))
                return
        try:
            conn.send({"rid": msg["rid"], "ok": False, "error": "ActorDiedError"})
        except ConnectionClosed:
            pass

    def _unpersist_actor(self, aid: str) -> None:
        if self.storage is not None:
            self.storage.delete("actors", aid)

    def _kill_actor(self, aid: str, no_restart: bool):
        fail: list[dict] = []
        # a kill with no_restart must stick across GCS restarts too
        if no_restart:
            self._unpersist_actor(aid)
        with self.lock:
            actor = self.actors.get(aid)
            if actor is None:
                return
            if no_restart:
                actor.restarts_left = 0
            actor.kill_requested = True
            w = self.workers.get(actor.worker) if actor.worker else None
            free_now: list[str] = []
            if w is None and actor.state in ("pending", "restarting"):
                # creation not yet dispatched: cancel it outright
                actor.state = "dead"
                self._unpersist_actor(actor.aid)
                self.publish("actor_state",
                             {"actor_id": actor.aid, "state": "dead"})
                self.pending_actor_creations = collections.deque(
                    s for s in self.pending_actor_creations if s["actor_id"] != aid
                )
                while actor.queue:
                    fail.append(actor.queue.popleft())
                actor.group_queued = 0
                for conn, rid in actor.waiters:
                    try:
                        conn.send({"rid": rid, "ok": False, "error": "ActorDiedError"})
                    except ConnectionClosed:
                        pass
                actor.waiters = []
                free_now = self._actor_dead_cleanup_locked(actor.create_spec)
                self.sched_traces.pop(aid, None)
                self._emit_event(
                    _const.EVENT_ACTOR_DEAD,
                    message=f"actor {actor.name or aid} killed before "
                            "creation dispatched",
                    actor_id=aid, name=actor.name,
                    death_reason="killed before creation")
        if free_now:
            self._free_objects(free_now)
        for spec in fail:
            self._fail_task_objects(spec, "actor killed before creation")
        if w is not None and not w.dead:
            try:
                w.conn.send({"type": "kill_actor", "aid": aid})
            except ConnectionClosed:
                pass
        # death will be observed via the worker connection closing

    # -------------------------------------------------------- placement groups

    def _create_pg(self, spec: dict, _persist: bool = True) -> str | None:
        with self.lock:
            if spec.get("strategy", "PACK") not in pg_policy.STRATEGIES:
                return (f"unknown placement strategy {spec.get('strategy')!r}; "
                        f"expected one of {pg_policy.STRATEGIES}")
            pg = _PG(spec["pg_id"], spec["bundles"], spec.get("strategy", "PACK"),
                     spec.get("name") or "")
            # feasibility against cluster totals (infeasible forever → error now;
            # reference raises on infeasible PGs too)
            class _TotNode:
                pass
            tot_nodes = []
            for n in self.nodes.values():
                if n.alive:
                    t = _TotNode()
                    t.node_id, t.total, t.available, t.labels, t.alive = (
                        n.node_id, n.total, dict(n.total), n.labels, True)
                    tot_nodes.append(t)
            if (_persist  # restore path: nodes re-register after start
                    and not self._autoscaler_conns  # growth may make it fit
                    and pg_policy.place_bundles(
                        tot_nodes, [b.total for b in pg.bundles], pg.strategy) is None):
                return ("placement group is infeasible: no node set satisfies "
                        f"{pg.strategy} over {spec['bundles']}")
            if pg.name:
                if pg.name in self.named_pgs and self.pgs[self.named_pgs[pg.name]].state != "removed":
                    return f"a placement group named {pg.name!r} already exists"
                self.named_pgs[pg.name] = pg.pg_id
            self.pgs[pg.pg_id] = pg
            self.objects.setdefault(pg_ready_oid(pg.pg_id),
                                    {"status": "pending", "where": None, "inline": None, "size": 0})
            self.pending_pgs.append(pg.pg_id)
            self._trace_enqueue(pg.pg_id, "pg")
        self._emit_event(
            _const.EVENT_PG_PENDING,
            message=f"placement group {pg.name or pg.pg_id} "
                    f"({pg.strategy}, {len(pg.bundles)} bundles) pending",
            pg_id=pg.pg_id, name=pg.name, strategy=pg.strategy,
            n_bundles=len(pg.bundles))
        if _persist and self.storage is not None:
            self.storage.put("pgs", spec["pg_id"], dict(spec))
        self._schedule()
        return None

    def _try_place_pgs_locked(self):
        """Called under lock from _schedule: try to place each pending PG."""
        import ray_tpu._private.serialization as ser

        placed: list[str] = []
        still = collections.deque()
        while self.pending_pgs:
            pg_id = self.pending_pgs.popleft()
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state != "pending":
                continue
            assignment = pg_policy.place_bundles(
                list(self.nodes.values()), [b.total for b in pg.bundles], pg.strategy)
            if assignment is None:
                still.append(pg_id)
                continue
            for b, node_id in zip(pg.bundles, assignment):
                b.node_id = node_id
                node = self.nodes[node_id]
                for k, v in b.total.items():
                    node.available[k] = node.available.get(k, 0) - v
            pg.state = "created"
            pg.epoch += 1
            placed.append(pg_id)
            placement = {str(i): b.node_id
                         for i, b in enumerate(pg.bundles)}
            tr = self.sched_traces.get(pg_id)
            wait = (time.monotonic() - tr["_enq_mono"]
                    if tr and tr.get("_enq_mono") is not None else None)
            self._observe_sched("pg", "placed", wait)
            self._trace_decision(pg_id, "placed", placement=placement,
                                 epoch=pg.epoch,
                                 queue_wait_s=(round(wait, 6)
                                               if wait is not None else None))
            self._emit_event(
                _const.EVENT_PG_CREATED,
                message=f"placement group {pg.name or pg_id} placed "
                        f"(epoch {pg.epoch})",
                pg_id=pg_id, name=pg.name, strategy=pg.strategy,
                placement=placement, epoch=pg.epoch)
            for conn, rid in pg.waiters:
                try:
                    conn.send({"rid": rid, "ok": True})
                except ConnectionClosed:
                    pass
            pg.waiters = []
        self.pending_pgs = still
        for pg_id in placed:
            blob = ser.dumps(True)
            oid = pg_ready_oid(pg_id)
            self.objects[oid] = {"status": "ready", "where": "inline", "inline": blob, "size": len(blob)}
            for conn, rid in self.object_waiters.pop(oid, []):
                self._reply_object(conn, rid, self.objects[oid])

    def _remove_pg(self, pg_id: str):
        if self.storage is not None:
            self.storage.delete("pgs", pg_id)
        import ray_tpu._private.serialization as ser
        from ray_tpu.exceptions import PlacementGroupUnschedulableError

        waiters: list[tuple[MsgConnection, int]] = []
        with self.lock:
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state == "removed":
                return
            if pg.state == "created":
                # return only the *unused* share now; in-flight tasks return
                # their share straight to the node on completion (_release_for)
                for b in pg.bundles:
                    node = self.nodes.get(b.node_id)
                    if node is not None and node.alive:
                        for k, v in b.available.items():
                            node.available[k] = node.available.get(k, 0) + v
            pg.state = "removed"
            waiters, pg.waiters = pg.waiters, []
            if pg.name and self.named_pgs.get(pg.name) == pg_id:
                del self.named_pgs[pg.name]
            self.pending_pgs = collections.deque(p for p in self.pending_pgs if p != pg_id)
            self.sched_traces.pop(pg_id, None)
            self._emit_event(
                _const.EVENT_PG_REMOVED,
                message=f"placement group {pg.name or pg_id} removed",
                pg_id=pg_id, name=pg.name)
        for conn, rid in waiters:
            try:
                conn.send({"rid": rid, "ok": False, "error": "placement group removed"})
            except ConnectionClosed:
                pass
        # resolve the ready-object as an error so get(pg.ready()) unblocks
        blob = ser.dumps(PlacementGroupUnschedulableError("placement group removed"))
        self._on_object_ready(pg_ready_oid(pg_id), where="inline", inline=blob,
                              size=len(blob), is_error=True)
        self._schedule()

    def _pg_wait(self, conn: MsgConnection, msg: dict):
        with self.lock:
            pg = self.pgs.get(msg["pg_id"])
            if pg is None:
                err = "no such placement group"
            elif pg.state == "created":
                conn.send({"rid": msg["rid"], "ok": True})
                return
            elif pg.state == "pending":
                pg.waiters.append((conn, msg["rid"]))
                return
            else:
                err = "placement group removed"
        try:
            conn.send({"rid": msg["rid"], "ok": False, "error": err})
        except ConnectionClosed:
            pass

    # ----------------------------------------------------------------- nodes

    def set_head_object_addr(self, addr: str) -> None:
        with self.lock:
            self.hosts[HEAD_HOST]["object_addr"] = addr

    def _remove_host(self, host_id: str):
        """A follower host's agent connection died: its nodes die with it."""
        with self.lock:
            if host_id not in self.hosts or host_id == HEAD_HOST:
                return
            self.hosts.pop(host_id, None)
            # a departed host's retained series must go with it, or the
            # metrics tab / node_mem_usage gauge serves dead nodes forever
            self.node_history.pop(host_id, None)
            doomed_nodes = [n for n, h in self.node_hosts.items() if h == host_id]
            # drop the host from every object's location set + accounting
            for entry in self.objects.values():
                entry.get("hosts", set()).discard(host_id)
                entry.get("shm_live", set()).discard(host_id)
            self.host_shm_bytes.pop(host_id, None)
        for node_id in doomed_nodes:
            self._remove_node(
                node_id,
                reason=f"host {host_id} connection lost / failed health checks")

    def _reapply_drain_locked(self, node: "_VNode") -> None:
        """Restore a persisted drain onto a (re)registering node: a drain
        record in kv means the node was marked DRAINING before a GCS
        restart / reconnect — it must come back unplaceable."""
        rec = self.kv.get(_DRAIN_KV_PREFIX + node.node_id)
        if rec:
            node.draining = True
            node.drain_reason = rec.get("reason") or ""
            node.drain_since = rec.get("ts")
            node.drain_grace = rec.get("grace_s")

    def _remove_node(self, node_id: str, reason: str = ""):
        """Mark a virtual node dead: its workers die, its PG bundles unplace."""
        to_fail: list[dict] = []
        unplaced_pgs: list[tuple[str, str]] = []
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            node.alive = False
            doomed = [w for w in self.workers.values()
                      if w.node_id == node_id and w.kind == "worker" and not w.dead]
            # PGs with bundles on this node go back to pending (reference: PG
            # rescheduling on node failure, gcs_placement_group_manager.h)
            for pg in self.pgs.values():
                if pg.state == "created" and any(b.node_id == node_id for b in pg.bundles):
                    for b in pg.bundles:
                        other = self.nodes.get(b.node_id)
                        if b.node_id != node_id and other is not None and other.alive:
                            for k, v in b.available.items():
                                other.available[k] = other.available.get(k, 0) + v
                        b.available = dict(b.total)
                        b.node_id = None
                    pg.state = "pending"
                    self.pending_pgs.append(pg.pg_id)
                    self._trace_enqueue(pg.pg_id, "pg")
                    unplaced_pgs.append((pg.pg_id, pg.name))
                    oid = pg_ready_oid(pg.pg_id)
                    self.objects[oid] = {"status": "pending", "where": None, "inline": None, "size": 0}
        self._emit_event(
            _const.EVENT_NODE_LEAVE,
            severity=_const.EVENT_SEVERITY_WARNING, node=node_id,
            message=f"node left the cluster: {reason or 'unknown cause'}",
            reason=reason, n_workers_lost=len(doomed))
        for pg_id_, pg_name_ in unplaced_pgs:
            self._emit_event(
                _const.EVENT_PG_PENDING,
                severity=_const.EVENT_SEVERITY_WARNING, node=node_id,
                message=f"placement group {pg_name_ or pg_id_} unplaced: "
                        f"node {node_id} died; bundles back to pending",
                pg_id=pg_id_, name=pg_name_,
                reason=f"node {node_id} died")
        for w in doomed:
            try:
                w.conn.send({"type": "exit"})
            except ConnectionClosed:
                pass
            self._on_worker_death(w.wid)
        self._schedule()

    # ------------------------------------------------------------ fault paths

    def _fail_task_objects(self, spec: dict, reason: str):
        """Mark all return objects of a task as errored (caller holds no lock)."""
        import ray_tpu._private.serialization as ser
        from ray_tpu.exceptions import (
            ActorDiedError,
            TaskCancelledError,
            WorkerCrashedError,
        )

        if spec.get("_cancelled"):
            exc = TaskCancelledError(reason)
        elif spec["kind"] == "actor_task":
            exc = ActorDiedError(reason)
        else:
            exc = WorkerCrashedError(reason)
        blob = ser.dumps(exc)
        with self.lock:
            free_now = self._sys_hold_locked(spec.pop("_holds", ()), -1)
        if free_now:
            self._free_objects(free_now)
        if spec["num_returns"] == "streaming":
            with self.lock:
                st = self.streams.get(spec["task_id"])
                if st is not None:
                    st["done"] = True
                    st["error"] = blob
                    waiters, st["waiters"] = st["waiters"], []
                else:
                    waiters = []
            for wconn, rid, idx in waiters:
                self._answer_stream_next(wconn, rid, spec["task_id"], idx)
            return
        for i in range(spec["num_returns"]):
            oid = f"{spec['task_id']}r{i:04d}"
            self._on_object_ready(oid, where="inline", inline=blob, size=len(blob), is_error=True)

    def _on_worker_death(self, wid: str):
        requeue: dict | None = None
        fail: list[dict] = []
        death_free: list[str] = []
        with self.lock:
            w = self.workers.get(wid)
            if w is None or w.dead:
                return
            w.dead = True
            # tasks failed here terminate WITHOUT a task_done message, which
            # can unpin lineage entries just like a completion — re-arm the
            # bounded eviction walk (see _retain_lineage_locked)
            self._lineage_evict_stalled = False
            # reclaim the process's outstanding ref contributions: a SIGKILL
            # (or a secondary driver disconnecting) must not pin objects its
            # flushed +1s were holding (reference: reference_counter borrower
            # death)
            for oid, bal in w.ref_balance.items():
                if not bal:
                    continue
                e = self.objects.get(oid)
                if e is None:
                    continue
                e["count"] = e.get("count", 0) - bal
                if self._freeable_locked(oid, e):
                    death_free.append(oid)
            w.ref_balance.clear()
            # pending stubs whose promised publisher is this process: the
            # object_put will never come, so fail them now instead of letting
            # borrowers block until their wait timeout (reference:
            # OwnerDiedError from the ownership directory). The promise index
            # keeps this O(promises by this wid), not O(all objects)
            orphaned_stubs = [
                oid for oid in self._pub_promises.pop(wid, ())
                if (e := self.objects.get(oid)) is not None
                and e.get("status") == "pending"
                and e.get("pub_wid") == wid]
            # fail parked RDT exports that were waiting on this process
            stale_exports = [(tok, waiter) for tok, waiter
                             in self._tensor_exports.items()
                             if waiter[2] == wid]
            for tok, _ in stale_exports:
                self._tensor_exports.pop(tok, None)
            if w.kind != "worker":
                # driver death: free its refs (outside the lock below); the
                # rest of the teardown is the node's job
                driver_death = True
            else:
                driver_death = False
        for _, (rconn, rrid, *_rest) in stale_exports:
            try:
                rconn.send({"rid": rrid, "ok": False,
                            "error": "owner process died during export"})
            except ConnectionClosed:
                pass
        if orphaned_stubs:
            self._fail_orphaned_stubs(orphaned_stubs)
        # leases HELD by the dying process: its workers may still be mid-task
        # on the direct plane, so don't hand them to the scheduler — retire
        # them (the reference kills workers leaked by dead drivers too)
        with self.lock:
            held = list(self._leases_by_holder.pop(wid, ()))
            # compiled DAGs registered by this driver die with it (their
            # channels/loops are gone); the registry must not serve ghosts
            for did in [d for d, r in self.compiled_dags.items()
                        if r.get("driver_wid") == wid]:
                self.compiled_dags.pop(did, None)
        for lw in held:
            self._release_lease(lw, None, make_idle=False)
            with self.lock:
                lw_w = self.workers.get(lw)
                exit_conn = lw_w.conn if lw_w is not None and not lw_w.dead else None
            if exit_conn is not None:
                try:
                    exit_conn.send({"type": "exit"})
                except ConnectionClosed:
                    pass
        if driver_death:
            if death_free:
                self._free_objects(death_free)
            return
        with self.lock:
            # a lease ON the dying worker: give its resources back
            if w.leased_to is not None:
                hs = self._leases_by_holder.get(w.leased_to)
                if hs is not None:
                    hs.discard(wid)
                w.leased_to = None
                w.lease_token = None
                if w.lease_spec is not None:
                    self._release_for(w.lease_spec)
                    w.lease_spec = None
            if w.tpu_chips:
                node = self.nodes.get(w.node_id)
                if node is not None and node.alive:
                    # same freshness window the death-reason tagging uses: a
                    # stale oom_why from a kill that never landed must not
                    # quarantine chips on an unrelated later death
                    if self._oom_fresh(w):
                        # SIGKILLed mid-grant: the physical device pool may
                        # be wedged — quarantine the chips instead of handing
                        # them to the next worker (which would hang in
                        # backend init). Re-enable via unquarantine_chips.
                        node.quarantined_chips.extend(w.tpu_chips)
                    else:
                        node.chip_pool.extend(w.tpu_chips)
            specs = list(w.running_tasks.values())
            w.running_tasks.clear()
            aid = w.actor_id
            if aid is None:
                for spec in specs:
                    if spec["kind"] == "task":
                        self._release_for(spec)
                        # a partially-emitted stream can't be retried (its
                        # items are already consumed); fail it instead
                        if (spec["num_returns"] != "streaming"
                                and spec.get("retries_used", 0) < spec.get("max_retries", 0)):
                            spec["retries_used"] = spec.get("retries_used", 0) + 1
                            requeue = spec
                        else:
                            fail.append(spec)
            else:
                actor = self.actors.get(aid)
                if actor is not None:
                    self._release_for(actor.create_spec)
                    will_restart = (actor.restarts_left != 0
                                    and actor.state != "dead")
                    # in-flight method calls: retried on the restarted
                    # actor while their per-spec budget lasts (reference:
                    # max_task_retries), else failed with ActorDiedError.
                    # Never retried: streams (items already consumed — same
                    # guard as the plain-task path above) and deaths caused
                    # by an explicit kill() (reference: ray.kill interrupts
                    # fail regardless of the retry budget)
                    can_retry = will_restart and not actor.kill_requested
                    # the kill this flag requested has now happened: clear
                    # it so a LATER accidental death of the restarted actor
                    # retries normally (and the alive-handler doesn't
                    # re-kill every future incarnation)
                    actor.kill_requested = False
                    retry_q = []
                    for s in specs:
                        if s["kind"] != "actor_task":
                            if s["kind"] == "actor_create":
                                fail.append(s)
                            continue
                        # spec-level override of the actor's budget: the
                        # compiled-DAG exec loop submits with 0 so a lost
                        # loop task FAILS (resolving the driver's liveness
                        # ref) instead of resurrecting a stale loop over
                        # dead channels on the restarted actor
                        mtr = s.get("max_task_retries",
                                    actor.max_task_retries)
                        used = s.get("retries_used", 0)
                        if (can_retry
                                and s["num_returns"] != "streaming"
                                and (mtr == -1 or used < mtr)):
                            s["retries_used"] = used + 1
                            retry_q.append(s)
                        else:
                            fail.append(s)
                    # lost calls run FIRST on the restarted actor, ahead of
                    # the queued backlog that never dispatched. Retried
                    # specs go back to QUEUED: shed their in-flight group
                    # stamp and recount the group-lane backlog.
                    for s in retry_q:
                        s.pop("_cgroup", None)
                    actor.queue.extendleft(reversed(retry_q))
                    actor.in_flight = 0
                    actor.group_in_flight = {}
                    actor.group_queued = sum(
                        1 for s in actor.queue
                        if actor.method_groups.get(s.get("method") or "")
                        is not None)
                    actor.worker = None
                    # same freshness window the chip quarantine above uses;
                    # the module-level death_reason is computed after the
                    # lock, so derive it locally for the causal event fields
                    dr = ((w.oom_why if self._oom_fresh(w) else None)
                          or f"worker {wid} died")
                    if will_restart:
                        if actor.restarts_left > 0:
                            actor.restarts_left -= 1
                        actor.state = "restarting"
                        actor.num_restarts += 1
                        actor.create_spec["_enq_ts"] = time.monotonic()
                        self._trace_enqueue(actor.aid, "actor")
                        self._emit_event(
                            _const.EVENT_ACTOR_RESTARTING,
                            severity=_const.EVENT_SEVERITY_WARNING,
                            node=w.node_id,
                            message=f"actor {actor.name or actor.aid} "
                                    f"restarting: {dr}",
                            actor_id=actor.aid, name=actor.name,
                            death_reason=dr, worker=wid,
                            num_restarts=actor.num_restarts,
                            restarts_left=actor.restarts_left)
                        self.publish("actor_state", {"actor_id": actor.aid,
                                                     "state": "restarting"})
                        self.pending_actor_creations.append(actor.create_spec)
                    else:
                        actor.state = "dead"
                        self._observe_sched("actor", "died", None)
                        self.sched_traces.pop(actor.aid, None)
                        self._emit_event(
                            _const.EVENT_ACTOR_DEAD,
                            severity=_const.EVENT_SEVERITY_ERROR,
                            node=w.node_id,
                            message=f"actor {actor.name or actor.aid} died: "
                                    f"{dr}",
                            actor_id=actor.aid, name=actor.name,
                            death_reason=dr, worker=wid,
                            num_restarts=actor.num_restarts)
                        self._unpersist_actor(actor.aid)
                        self.publish("actor_state",
                                     {"actor_id": actor.aid, "state": "dead"})
                        while actor.queue:
                            fail.append(actor.queue.popleft())
                        actor.group_queued = 0
                        for conn, rid in actor.waiters:
                            try:
                                conn.send({"rid": rid, "ok": False, "error": "ActorDiedError"})
                            except ConnectionClosed:
                                pass
                        actor.waiters = []
                        death_free.extend(
                            self._actor_dead_cleanup_locked(actor.create_spec))
        if death_free:
            self._free_objects(death_free)
        death_reason = (w.oom_why if self._oom_fresh(w) else None) or f"worker {wid} died"
        for spec in fail:
            self._fail_task_objects(
                spec, "task was cancelled" if spec.get("_cancelled")
                else death_reason)
        if requeue is not None:
            with self.lock:
                self.pending_tasks.appendleft(requeue)
        self._schedule()
