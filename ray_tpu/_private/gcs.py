"""GCS — the control plane: object directory, scheduler, actor manager, KV.

One process-wide server thread accepting unix-socket connections from the
driver and worker processes. Collapses the reference's head-node GcsServer +
per-node raylet NodeManager into one component, keeping the same
responsibilities and state machines:

- object directory + waiters      (reference: src/ray/gcs/gcs_server.h pubsub,
                                   object_manager/ownership_object_directory.h)
- lease-style task scheduling     (reference: raylet/scheduling/cluster_lease_manager.h:41
                                   + local_lease_manager.h:60 — tasks are queued until
                                   deps are local and resources free, then dispatched)
- actor lifecycle + restarts      (reference: gcs/gcs_actor_manager.h:93)
- named actors, internal KV       (reference: gcs/gcs_kv_manager.h:34)
- worker pool scale-up            (reference: raylet/worker_pool.h:280)

Single-node v1: multi-node federation (one GCS + per-node raylets over TCP) is
the round-2 step; message types are already node-agnostic.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable

from ray_tpu._private.protocol import ConnectionClosed, MsgConnection, listen_unix

logger = logging.getLogger(__name__)

INLINE_LIMIT = 64 * 1024  # results smaller than this are stored in the GCS table


class _Worker:
    __slots__ = ("wid", "conn", "pid", "idle", "actor_id", "dead", "kind", "running_task")

    def __init__(self, wid: str, conn: MsgConnection, pid: int, kind: str):
        self.wid = wid
        self.conn = conn
        self.pid = pid
        self.kind = kind  # "worker" | "driver"
        self.idle = kind == "worker"
        self.actor_id: str | None = None
        self.running_task: dict | None = None
        self.dead = False


class _Actor:
    __slots__ = (
        "aid", "state", "worker", "queue", "busy", "create_spec", "name",
        "restarts_left", "waiters", "kill_requested",
    )

    def __init__(self, aid: str, create_spec: dict):
        self.aid = aid
        self.state = "pending"  # pending → alive → (restarting → alive)* → dead
        self.worker: str | None = None
        self.queue: collections.deque[dict] = collections.deque()
        self.busy = False
        self.create_spec = create_spec
        self.name: str | None = create_spec.get("name")
        self.restarts_left: int = create_spec.get("max_restarts", 0)
        self.waiters: list[tuple[MsgConnection, int]] = []  # ready-waiters
        self.kill_requested = False


class GcsServer:
    def __init__(
        self,
        socket_path: str,
        total_resources: dict[str, float],
        spawn_worker_cb: Callable[[int], None],
        max_workers: int = 32,
    ):
        self.socket_path = socket_path
        self.lock = threading.RLock()
        self.total = dict(total_resources)
        self.available = dict(total_resources)
        self.spawn_worker_cb = spawn_worker_cb
        self.max_workers = max_workers

        self.objects: dict[str, dict] = {}
        self.object_waiters: dict[str, list[tuple[MsgConnection, int]]] = {}
        self.workers: dict[str, _Worker] = {}
        self.pending_tasks: collections.deque[dict] = collections.deque()
        self.pending_actor_creations: collections.deque[dict] = collections.deque()
        self.actors: dict[str, _Actor] = {}
        self.named_actors: dict[str, str] = {}
        self.kv: dict[str, bytes] = {}
        self._spawn_pending: collections.deque[float] = collections.deque()
        self.stopped = False
        self._conn_threads: list[threading.Thread] = []
        self._listener = None
        self._accept_thread: threading.Thread | None = None
        # metrics / introspection
        self.task_counter = collections.Counter()

    # ------------------------------------------------------------------ server

    def start(self):
        self._listener = listen_unix(self.socket_path)
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True, name="gcs-accept")
        self._accept_thread.start()

    def stop(self):
        with self.lock:
            self.stopped = True
            for w in self.workers.values():
                if w.kind == "worker" and not w.dead:
                    try:
                        w.conn.send({"type": "exit"})
                    except ConnectionClosed:
                        pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self):
        while not self.stopped:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            conn = MsgConnection(sock)
            t = threading.Thread(target=self._serve_conn, args=(conn,), daemon=True, name="gcs-conn")
            t.start()
            self._conn_threads.append(t)

    def _serve_conn(self, conn: MsgConnection):
        wid = None
        try:
            while True:
                msg = conn.recv()
                wid = self._handle(conn, msg, wid)
        except ConnectionClosed:
            if wid is not None:
                self._on_worker_death(wid)

    # --------------------------------------------------------------- dispatch

    def _handle(self, conn: MsgConnection, msg: dict, wid: str | None) -> str | None:
        t = msg["type"]
        if t == "register":
            with self.lock:
                wid = msg["wid"]
                self.workers[wid] = _Worker(wid, conn, msg.get("pid", 0), msg["kind"])
                if msg["kind"] == "worker" and self._spawn_pending:
                    self._spawn_pending.popleft()
            conn.send({"rid": msg["rid"], "ok": True})
            self._schedule()
            return wid
        if t == "submit_task":
            self._submit_task(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "task_done":
            self._on_task_done(msg)
        elif t == "object_put":
            self._on_object_ready(msg["oid"], where=msg.get("where", "shm"),
                                  inline=msg.get("inline"), size=msg.get("size", 0),
                                  is_error=False)
        elif t == "wait_object":
            self._wait_object(conn, msg)
        elif t == "free_objects":
            with self.lock:
                for oid in msg["oids"]:
                    self.objects.pop(oid, None)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "create_actor":
            err = self._create_actor(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": err is None, "error": err})
        elif t == "actor_task":
            ok, err = self._submit_actor_task(msg["spec"])
            conn.send({"rid": msg["rid"], "ok": ok, "error": err})
        elif t == "wait_actor_ready":
            self._wait_actor_ready(conn, msg)
        elif t == "get_named_actor":
            with self.lock:
                aid = self.named_actors.get(msg["name"])
                state = self.actors[aid].state if aid else None
            conn.send({"rid": msg["rid"], "aid": aid, "state": state})
        elif t == "kill_actor":
            self._kill_actor(msg["aid"], msg.get("no_restart", True))
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "kv_put":
            with self.lock:
                self.kv[msg["key"]] = msg["value"]
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "kv_get":
            with self.lock:
                val = self.kv.get(msg["key"])
            conn.send({"rid": msg["rid"], "value": val})
        elif t == "kv_keys":
            with self.lock:
                keys = [k for k in self.kv if k.startswith(msg.get("prefix", ""))]
            conn.send({"rid": msg["rid"], "keys": keys})
        elif t == "kv_del":
            with self.lock:
                self.kv.pop(msg["key"], None)
            conn.send({"rid": msg["rid"], "ok": True})
        elif t == "cluster_state":
            with self.lock:
                state = {
                    "total_resources": dict(self.total),
                    "available_resources": dict(self.available),
                    "num_workers": sum(1 for w in self.workers.values() if w.kind == "worker" and not w.dead),
                    "num_actors": sum(1 for a in self.actors.values() if a.state == "alive"),
                    "pending_tasks": len(self.pending_tasks),
                    "task_counter": dict(self.task_counter),
                    "actors": {
                        a.aid: {"state": a.state, "name": a.name, "worker": a.worker}
                        for a in self.actors.values()
                    },
                }
            conn.send({"rid": msg["rid"], "state": state})
        else:
            logger.warning("gcs: unknown message type %s", t)
        return wid

    # --------------------------------------------------------------- objects

    def _on_object_ready(self, oid: str, where: str, inline, size: int, is_error: bool):
        with self.lock:
            self.objects[oid] = {
                "status": "error" if is_error else "ready",
                "where": where,
                "inline": inline,
                "size": size,
            }
            waiters = self.object_waiters.pop(oid, [])
            entry = self.objects[oid]
        for conn, rid in waiters:
            self._reply_object(conn, rid, entry)
        self._schedule()

    def _reply_object(self, conn: MsgConnection, rid: int, entry: dict):
        try:
            conn.send({
                "rid": rid, "ready": True, "status": entry["status"],
                "where": entry["where"], "inline": entry["inline"], "size": entry["size"],
            })
        except ConnectionClosed:
            pass

    def _wait_object(self, conn: MsgConnection, msg: dict):
        oid = msg["oid"]
        with self.lock:
            entry = self.objects.get(oid)
            if entry is None or entry["status"] == "pending":
                self.object_waiters.setdefault(oid, []).append((conn, msg["rid"]))
                return
        self._reply_object(conn, msg["rid"], entry)

    # ----------------------------------------------------------------- tasks

    def _submit_task(self, spec: dict):
        with self.lock:
            for i in range(spec["num_returns"]):
                oid = f"{spec['task_id']}r{i:04d}"
                self.objects.setdefault(oid, {"status": "pending", "where": None, "inline": None, "size": 0})
            self.pending_tasks.append(spec)
            self.task_counter["submitted"] += 1
        self._schedule()

    def _deps_ready(self, spec: dict) -> bool:
        for dep in spec.get("deps", ()):
            e = self.objects.get(dep)
            if e is None or e["status"] == "pending":
                return False
        return True

    def _fits(self, resources: dict) -> bool:
        return all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in resources.items())

    def _acquire(self, resources: dict):
        for k, v in resources.items():
            self.available[k] = self.available.get(k, 0.0) - v

    def _release(self, resources: dict):
        for k, v in resources.items():
            self.available[k] = self.available.get(k, 0.0) + v

    def _schedule(self):
        """Dispatch whatever can run; request worker scale-up for the rest."""
        to_send: list[tuple[MsgConnection, dict]] = []
        want_spawn = 0
        with self.lock:
            if self.stopped:
                return
            idle = [w for w in self.workers.values()
                    if w.kind == "worker" and w.idle and not w.dead and w.actor_id is None]

            # actor creations first (they pin workers)
            still_pending = collections.deque()
            while self.pending_actor_creations:
                spec = self.pending_actor_creations.popleft()
                actor = self.actors.get(spec["actor_id"])
                if actor is None or actor.state == "dead":
                    continue
                res = spec.get("resources", {})
                if idle and self._fits(res) and self._deps_ready(spec):
                    w = idle.pop()
                    self._acquire(res)
                    w.idle = False
                    w.actor_id = spec["actor_id"]
                    w.running_task = spec
                    actor.worker = w.wid
                    to_send.append((w.conn, {"type": "exec", "spec": spec}))
                else:
                    still_pending.append(spec)
            self.pending_actor_creations = still_pending

            # normal tasks
            still = collections.deque()
            while self.pending_tasks:
                spec = self.pending_tasks.popleft()
                res = spec.get("resources", {})
                if idle and self._fits(res) and self._deps_ready(spec):
                    w = idle.pop()
                    self._acquire(res)
                    w.idle = False
                    w.running_task = spec
                    to_send.append((w.conn, {"type": "exec", "spec": spec}))
                else:
                    still.append(spec)
            self.pending_tasks = still

            # actor method calls
            for actor in self.actors.values():
                if actor.state == "alive" and not actor.busy and actor.queue:
                    w = self.workers.get(actor.worker)
                    if w is None or w.dead:
                        continue
                    spec = actor.queue.popleft()
                    actor.busy = True
                    w.running_task = spec
                    to_send.append((w.conn, {"type": "exec", "spec": spec}))

            # scale-up: runnable-if-only-there-were-workers
            now = time.monotonic()
            while self._spawn_pending and now - self._spawn_pending[0] > 60.0:
                self._spawn_pending.popleft()  # spawn presumed failed; allow retry
            spawning = len(self._spawn_pending)
            demand = len(self.pending_tasks) + len(self.pending_actor_creations)
            n_workers = sum(1 for w in self.workers.values() if w.kind == "worker" and not w.dead)
            if demand > 0:
                headroom = self.max_workers - n_workers - spawning
                want_spawn = max(0, min(demand - len(idle) - spawning, headroom))
                for _ in range(want_spawn):
                    self._spawn_pending.append(now)

        for conn, msg in to_send:
            try:
                conn.send(msg)
            except ConnectionClosed:
                pass
        if want_spawn > 0:
            self.spawn_worker_cb(want_spawn)

    def _on_task_done(self, msg: dict):
        wid = msg["wid"]
        ready: list[tuple[str, dict]] = []
        with self.lock:
            w = self.workers.get(wid)
            spec = msg["spec"]
            kind = spec["kind"]
            res = spec.get("resources", {})
            if w is not None:
                w.running_task = None
            error = msg.get("error")
            if kind == "actor_create":
                actor = self.actors.get(spec["actor_id"])
                if error is None:
                    if actor is not None:
                        actor.state = "alive"
                        waiters, actor.waiters = actor.waiters, []
                        for conn, rid in waiters:
                            try:
                                conn.send({"rid": rid, "ok": True})
                            except ConnectionClosed:
                                pass
                        if actor.kill_requested and w is not None and not w.dead:
                            try:
                                w.conn.send({"type": "kill_actor", "aid": actor.aid})
                            except ConnectionClosed:
                                pass
                else:
                    # creation failed → actor dead, release worker
                    if actor is not None:
                        actor.state = "dead"
                        for conn, rid in actor.waiters:
                            try:
                                conn.send({"rid": rid, "ok": False, "error": error})
                            except ConnectionClosed:
                                pass
                        actor.waiters = []
                    if w is not None:
                        w.actor_id = None
                        w.idle = True
                    self._release(res)
            else:
                if kind == "actor_task":
                    actor = self.actors.get(spec["actor_id"])
                    if actor is not None:
                        actor.busy = False
                else:
                    if w is not None:
                        w.idle = True
                    self._release(res)
            self.task_counter["finished" if error is None else "failed"] += 1

            # record results
            for oid, where, inline, size in msg.get("results", ()):
                self.objects[oid] = {
                    "status": "error" if error is not None else "ready",
                    "where": where, "inline": inline, "size": size,
                }
                for conn, rid in self.object_waiters.pop(oid, []):
                    self._reply_object(conn, rid, self.objects[oid])
        self._schedule()

    # ---------------------------------------------------------------- actors

    def _create_actor(self, spec: dict) -> str | None:
        with self.lock:
            aid = spec["actor_id"]
            actor = _Actor(aid, spec)
            if actor.name:
                existing = self.named_actors.get(actor.name)
                if existing is not None and self.actors[existing].state != "dead":
                    return f"an actor named {actor.name!r} already exists"
                self.named_actors[actor.name] = aid
            self.actors[aid] = actor
            self.pending_actor_creations.append(spec)
        self._schedule()
        return None

    def _submit_actor_task(self, spec: dict) -> tuple[bool, str | None]:
        with self.lock:
            actor = self.actors.get(spec["actor_id"])
            if actor is None or actor.state == "dead":
                return False, "ActorDiedError"
            for i in range(spec["num_returns"]):
                oid = f"{spec['task_id']}r{i:04d}"
                self.objects.setdefault(oid, {"status": "pending", "where": None, "inline": None, "size": 0})
            actor.queue.append(spec)
        self._schedule()
        return True, None

    def _wait_actor_ready(self, conn: MsgConnection, msg: dict):
        with self.lock:
            actor = self.actors.get(msg["aid"])
            if actor is None:
                pass
            elif actor.state == "alive":
                conn.send({"rid": msg["rid"], "ok": True})
                return
            elif actor.state in ("pending", "restarting"):
                actor.waiters.append((conn, msg["rid"]))
                return
        try:
            conn.send({"rid": msg["rid"], "ok": False, "error": "ActorDiedError"})
        except ConnectionClosed:
            pass

    def _kill_actor(self, aid: str, no_restart: bool):
        fail: list[dict] = []
        with self.lock:
            actor = self.actors.get(aid)
            if actor is None:
                return
            if no_restart:
                actor.restarts_left = 0
            actor.kill_requested = True
            w = self.workers.get(actor.worker) if actor.worker else None
            if w is None and actor.state in ("pending", "restarting"):
                # creation not yet dispatched: cancel it outright
                actor.state = "dead"
                self.pending_actor_creations = collections.deque(
                    s for s in self.pending_actor_creations if s["actor_id"] != aid
                )
                while actor.queue:
                    fail.append(actor.queue.popleft())
                for conn, rid in actor.waiters:
                    try:
                        conn.send({"rid": rid, "ok": False, "error": "ActorDiedError"})
                    except ConnectionClosed:
                        pass
                actor.waiters = []
        for spec in fail:
            self._fail_task_objects(spec, "actor killed before creation")
        if w is not None and not w.dead:
            try:
                w.conn.send({"type": "kill_actor", "aid": aid})
            except ConnectionClosed:
                pass
        # death will be observed via the worker connection closing

    # ------------------------------------------------------------ fault paths

    def _fail_task_objects(self, spec: dict, reason: str):
        """Mark all return objects of a task as errored (caller holds no lock)."""
        import ray_tpu._private.serialization as ser
        from ray_tpu.exceptions import WorkerCrashedError, ActorDiedError

        exc = ActorDiedError(reason) if spec["kind"] == "actor_task" else WorkerCrashedError(reason)
        blob = ser.dumps(exc)
        for i in range(spec["num_returns"]):
            oid = f"{spec['task_id']}r{i:04d}"
            self._on_object_ready(oid, where="inline", inline=blob, size=len(blob), is_error=True)

    def _on_worker_death(self, wid: str):
        requeue: dict | None = None
        fail: list[dict] = []
        with self.lock:
            w = self.workers.get(wid)
            if w is None or w.dead:
                return
            w.dead = True
            if w.kind != "worker":
                return  # driver death handled by node teardown
            spec = w.running_task
            aid = w.actor_id
            if aid is None:
                self._release({} if spec is None else spec.get("resources", {}) if spec["kind"] == "task" else {})
                if spec is not None and spec["kind"] == "task":
                    if spec.get("retries_used", 0) < spec.get("max_retries", 0):
                        spec["retries_used"] = spec.get("retries_used", 0) + 1
                        requeue = spec
                    else:
                        fail.append(spec)
            else:
                actor = self.actors.get(aid)
                create_res = actor.create_spec.get("resources", {}) if actor else {}
                self._release(create_res)
                if actor is not None:
                    if spec is not None and spec["kind"] in ("actor_task", "actor_create"):
                        fail.append(spec)
                    actor.busy = False
                    actor.worker = None
                    if actor.restarts_left != 0 and actor.state != "dead":
                        if actor.restarts_left > 0:
                            actor.restarts_left -= 1
                        actor.state = "restarting"
                        self.pending_actor_creations.append(actor.create_spec)
                    else:
                        actor.state = "dead"
                        while actor.queue:
                            fail.append(actor.queue.popleft())
                        for conn, rid in actor.waiters:
                            try:
                                conn.send({"rid": rid, "ok": False, "error": "ActorDiedError"})
                            except ConnectionClosed:
                                pass
                        actor.waiters = []
        for spec in fail:
            self._fail_task_objects(spec, f"worker {wid} died")
        if requeue is not None:
            with self.lock:
                self.pending_tasks.appendleft(requeue)
        self._schedule()
