"""Persistent GCS table storage — the fault-tolerance backend.

Reference capability: pluggable GCS metadata persistence
(src/ray/gcs/store_client/ — InMemoryStoreClient vs RedisStoreClient:126;
Redis mode lets the GCS restart and rebuild its managers from stored tables
via gcs_init_data.h). TPU build keeps it dependency-free: sqlite3 (stdlib)
in WAL mode, one table per GCS manager, write-through on every mutation.

Tables: kv (internal KV incl. jobs), actors (create specs of live actors),
pgs (placement-group specs), session (session metadata), instances
(autoscaler instance state machine — see autoscaler/instance_manager.py),
serve (serve control-plane state — see serve/controller.py recovery),
events (INFO+ cluster events — see _private/events.py; keyed by
zero-padded sequence number so restart recovery replays them in order).
"""

from __future__ import annotations

import os
import pickle
import sqlite3
import threading
from typing import Any, Iterator, Optional


#: every persisted GCS table. The graft_check rpc-pairing checker verifies
#: that any table literal the GCS server reads/writes appears here, so a
#: handler can never target a table this module never created.
TABLES = ("kv", "actors", "pgs", "session", "instances", "serve", "events")


class GcsStorage:
    """Write-through table store. All methods are thread-safe."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        for table in TABLES:
            self._db.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                "(key TEXT PRIMARY KEY, value BLOB)")
        self._db.commit()

    def put(self, table: str, key: str, value: Any) -> None:
        blob = pickle.dumps(value, protocol=5)
        with self._lock:
            self._db.execute(
                f"INSERT OR REPLACE INTO {table} (key, value) VALUES (?, ?)",
                (key, blob))
            self._db.commit()

    def get(self, table: str, key: str) -> Optional[Any]:
        with self._lock:
            row = self._db.execute(
                f"SELECT value FROM {table} WHERE key = ?", (key,)).fetchone()
        return pickle.loads(row[0]) if row else None

    def delete(self, table: str, key: str) -> None:
        with self._lock:
            self._db.execute(f"DELETE FROM {table} WHERE key = ?", (key,))
            self._db.commit()

    def items(self, table: str) -> Iterator[tuple[str, Any]]:
        with self._lock:
            rows = self._db.execute(
                f"SELECT key, value FROM {table}").fetchall()
        for k, v in rows:
            yield k, pickle.loads(v)

    def close(self) -> None:
        with self._lock:
            try:
                self._db.commit()
                self._db.close()
            except sqlite3.Error:
                pass
