"""Binary IDs for objects, tasks, actors, workers, placement groups.

TPU-native analogue of the reference's id vocabulary
(reference: src/ray/common/id.h — JobID/ActorID/TaskID/ObjectID). We keep the
same structural idea (ObjectIDs derive from the producing TaskID + return
index, so lineage is recoverable from the ID itself) without the bit-packed
binary layout.
"""

from __future__ import annotations

import os
import threading

_HEX = "0123456789abcdef"


def _rand_hex(n: int = 16) -> str:
    return os.urandom(n).hex()


class BaseID:
    __slots__ = ("_hex",)
    _prefix = "id"

    def __init__(self, hex_str: str | None = None):
        self._hex = hex_str if hex_str is not None else _rand_hex()

    def hex(self) -> str:
        return self._hex

    def __eq__(self, other):
        return type(other) is type(self) and other._hex == self._hex

    def __hash__(self):
        return hash((self._prefix, self._hex))

    def __repr__(self):
        return f"{type(self).__name__}({self._hex[:8]}…)"

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    @classmethod
    def from_hex(cls, h: str):
        return cls(h)


class TaskID(BaseID):
    _prefix = "task"


class ActorID(BaseID):
    _prefix = "actor"


class WorkerID(BaseID):
    _prefix = "worker"


class NodeID(BaseID):
    _prefix = "node"


class PlacementGroupID(BaseID):
    _prefix = "pg"


class ObjectID(BaseID):
    """ObjectID = <task hex>:<return index>, or a pure random id for ray.put.

    Embedding the producing task makes lineage reconstruction possible from
    the ID alone (reference: src/ray/common/id.h object-id structure).
    """

    _prefix = "obj"

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(f"{task_id.hex()}r{index:04d}")

    @classmethod
    def for_put(cls) -> "ObjectID":
        return cls(f"{_rand_hex()}p0000")

    def task_id(self) -> TaskID | None:
        if self._hex.endswith("p0000"):
            return None
        base, _, _ = self._hex.rpartition("r")
        return TaskID(base) if base else None

    def return_index(self) -> int:
        _, _, idx = self._hex.rpartition("r")
        try:
            return int(idx)
        except ValueError:
            return 0


_local_counter = threading.local()
