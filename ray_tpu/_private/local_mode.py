"""Local mode: the whole API executed inline in the driver process.

(reference: ray.init(local_mode=True) — used for fast library tests, e.g.
serve's local_testing_mode, serve/_private/local_testing_mode.py:244.)
Implements the same surface as CoreWorker, so the public API layer does not
branch on mode.
"""

from __future__ import annotations

import traceback
from typing import Any, Sequence

from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.worker import ObjectRef
from ray_tpu.exceptions import ActorDiedError, RayTaskError


class LocalWorker:
    kind = "local"

    def __init__(self):
        self._objects: dict[str, tuple[bool, Any]] = {}  # oid -> (is_error, value)
        self.actors: dict[str, Any] = {}
        self._named: dict[str, str] = {}
        self._dead_actors: set[str] = set()

    # objects
    def put(self, value: Any, pin: bool = False) -> ObjectRef:
        oid = ObjectID.for_put().hex()
        self._objects[oid] = (False, value)
        return ObjectRef(oid)

    def get_object(self, oid: str, timeout=None) -> Any:
        is_error, value = self._objects[oid]
        if is_error:
            raise value
        return value

    def get(self, refs, timeout=None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        out = [self.get_object(r.hex()) for r in refs]
        return out[0] if single else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1, timeout=None):
        return list(refs[:num_returns]), list(refs[num_returns:])

    def free(self, refs):
        for r in refs:
            self._objects.pop(r.hex(), None)

    # tasks
    def _run(self, fn, args, kwargs, task_id: str, num_returns: int, name: str):
        try:
            out = fn(*args, **kwargs)
            values = [out] if num_returns == 1 else (list(out) if num_returns else [])
            for i, v in enumerate(values):
                self._objects[f"{task_id}r{i:04d}"] = (False, v)
        except Exception as e:  # noqa: BLE001
            wrapped = RayTaskError(name, traceback.format_exc(), e)
            for i in range(num_returns):
                self._objects[f"{task_id}r{i:04d}"] = (True, wrapped)
        return [ObjectRef(f"{task_id}r{i:04d}") for i in range(num_returns)]

    def submit_task(self, func_blob, args, kwargs, *, func_sha=None, num_returns=1, resources=None,
                    max_retries=0, name="", strategy=None, runtime_env=None):
        fn = ser.loads(func_blob) if isinstance(func_blob, bytes) else func_blob
        args = tuple(self.get_object(a.hex()) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: self.get_object(v.hex()) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()}
        return self._run(fn, args, kwargs, TaskID().hex(), num_returns, name)

    # actors
    def create_actor(self, cls_blob, args, kwargs, *, resources=None, max_restarts=0, max_task_retries=0,
                     name=None, namespace=None, strategy=None,
                     max_concurrency=1, runtime_env=None,
                     concurrency_groups=None, concurrency_group_methods=None,
                     class_name=None):
        cls = ser.loads(cls_blob) if isinstance(cls_blob, bytes) else cls_blob
        aid = ActorID().hex()
        args = tuple(self.get_object(a.hex()) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: self.get_object(v.hex()) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()}
        self.actors[aid] = cls(*args, **kwargs)
        if name:
            self._named[(namespace or self.namespace, name)] = aid
        return aid

    def submit_actor_task(self, actor_id, method_name, args, kwargs, *,
                          num_returns=1, max_task_retries=None):
        if actor_id in self._dead_actors:
            # match cluster mode: dead-actor submission yields refs whose
            # get() raises (the reference errors at get, not .remote())
            tid = TaskID().hex()
            err = ActorDiedError(f"actor {actor_id[:8]} is dead")
            n = num_returns if isinstance(num_returns, int) else 1
            for i in range(n):
                self._objects[f"{tid}r{i:04d}"] = (True, err)
            return [ObjectRef(f"{tid}r{i:04d}") for i in range(n)]
        instance = self.actors[actor_id]
        args = tuple(self.get_object(a.hex()) if isinstance(a, ObjectRef) else a for a in args)
        kwargs = {k: self.get_object(v.hex()) if isinstance(v, ObjectRef) else v for k, v in kwargs.items()}
        return self._run(getattr(instance, method_name), args, kwargs, TaskID().hex(),
                         num_returns, method_name)

    def wait_actor_ready(self, actor_id, timeout=None):
        if actor_id in self._dead_actors:
            raise ActorDiedError("actor is dead")

    def kill_actor(self, actor_id, no_restart=True):
        self.actors.pop(actor_id, None)
        self._dead_actors.add(actor_id)

    namespace = "default"

    def effective_namespace(self):
        return self.namespace

    def get_named_actor(self, name, namespace=None):
        # namespace-scoped exactly like cluster mode: local-mode tests must
        # not silently resolve across namespaces
        return self._named.get((namespace or self.namespace, name))

    # kv
    def __init_kv(self):
        if not hasattr(self, "_kv"):
            self._kv = {}
        return self._kv

    def kv_put(self, key, value):
        self.__init_kv()[key] = value

    def kv_get(self, key):
        return self.__init_kv().get(key)

    def kv_keys(self, prefix=""):
        return [k for k in self.__init_kv() if k.startswith(prefix)]

    def kv_del(self, key):
        self.__init_kv().pop(key, None)

    # placement groups: trivially satisfied inline
    def create_pg(self, pg_id, bundles, strategy, name=""):
        if not hasattr(self, "_pgs"):
            self._pgs = {}
        self._pgs[pg_id] = {"name": name, "state": "created", "strategy": strategy,
                            "bundles": bundles, "bundle_nodes": ["node-0"] * len(bundles)}
        from ray_tpu._private.gcs import pg_ready_oid

        self._objects[pg_ready_oid(pg_id)] = (False, True)
        if name:
            self.__init_kv()[f"__pg_name:{name}"] = pg_id

    def remove_pg(self, pg_id):
        if hasattr(self, "_pgs") and pg_id in self._pgs:
            self._pgs[pg_id]["state"] = "removed"

    def pg_wait(self, pg_id, timeout=None):
        return hasattr(self, "_pgs") and self._pgs.get(pg_id, {}).get("state") == "created"

    def pg_table(self):
        return dict(getattr(self, "_pgs", {}))

    def get_named_pg(self, name):
        return self.__init_kv().get(f"__pg_name:{name}")

    def add_node(self, node_id, resources, labels=None):
        pass

    def remove_node(self, node_id):
        pass

    def list_nodes(self):
        return [{"node_id": "node-0", "alive": True, "labels": {},
                 "total": {"CPU": 1.0}, "available": {"CPU": 1.0}}]

    def cluster_state(self):
        return {
            "total_resources": {"CPU": 1.0},
            "available_resources": {"CPU": 1.0},
            "num_workers": 0,
            "num_actors": len(self.actors),
            "pending_tasks": 0,
            "task_counter": {},
            "actors": {},
        }

    def disconnect(self):
        pass
