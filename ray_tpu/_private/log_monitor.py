"""Log monitor: tail worker log files and republish lines to the driver.

(reference capability: python/ray/_private/log_monitor.py — a per-node
process tails `session_latest/logs/*` and publishes through GCS pubsub to
every driver; here the driver runs the tailer in-process over the session's
log dir, which covers the single-host layout. The follower-node agent runs
its own tailer and forwards over the wire — see node agent.)
"""

from __future__ import annotations

import os
import sys
import threading
import time


class LogMonitor:
    """Polls `<session>/logs/*.log` for appended bytes; emits each complete
    line to `sink(source, line)`. Default sink prints to stderr in the
    reference's `(worker-N pid=…)` style."""

    def __init__(self, log_dir: str, sink=None, poll_interval_s: float = 0.25):
        self.log_dir = log_dir
        self.sink = sink or self._default_sink
        self.poll_interval_s = poll_interval_s
        self._offsets: dict[str, int] = {}
        self._partial: dict[str, bytes] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="log-monitor")

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._poll()  # final drain so shutdown doesn't eat tail lines

    @staticmethod
    def _default_sink(source: str, line: str):
        print(f"({source}) {line}", file=sys.stderr)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._poll()
            except Exception:
                pass  # session dir may vanish at shutdown
            self._stop.wait(self.poll_interval_s)

    def _poll(self):
        try:
            names = sorted(os.listdir(self.log_dir))
        except OSError:
            return
        for name in names:
            if not name.endswith(".log"):
                continue
            path = os.path.join(self.log_dir, name)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(name, 0)
            if size <= off:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read(size - off)
            except OSError:
                continue
            self._offsets[name] = off + len(data)
            buf = self._partial.pop(name, b"") + data
            *lines, rest = buf.split(b"\n")
            if rest:
                self._partial[name] = rest
            source = name[:-len(".log")]
            for raw in lines:
                line = raw.decode("utf-8", "replace")
                if line.strip():
                    self.sink(source, line)
