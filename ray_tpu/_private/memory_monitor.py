"""Host memory monitor + worker-killing policy (OOM defense).

Watches host memory usage; past the threshold it kills a victim worker so
the kernel OOM killer never takes down the node agent / GCS with it. Victim
choice follows the reference's group-by-owner policy shape: prefer the
NEWEST retriable running task's worker (its lost work is the cheapest and it
can be retried), then leased direct-dispatch workers (their callers retry),
never infrastructure processes.

(reference: src/ray/common/memory_monitor.h:52 — usage polling with
threshold; src/ray/raylet/worker_killing_policy_group_by_owner.h:87 —
newest-retriable-first victim choice; VERDICT round-2 item 5.)

Enabled when RAY_TPU_MEMORY_MONITOR_REFRESH_MS > 0 (the GCS enables it for
the head host, each node agent for its own host). Tests can fake the usage
reading via RAY_TPU_TESTING_MEM_USAGE_FILE (a file holding a float 0..1).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable

logger = logging.getLogger(__name__)

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def host_memory_usage() -> float:
    """Fraction of host memory in use (1 - MemAvailable/MemTotal)."""
    override = os.environ.get("RAY_TPU_TESTING_MEM_USAGE_FILE")
    if override:
        try:
            return float(open(override).read().strip())
        except (OSError, ValueError):
            return 0.0
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = float(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0.0
    if not total or avail is None:
        return 0.0
    return 1.0 - avail / total


def proc_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


class MemoryMonitor:
    """Generic monitor loop: `pick_victim()` returns (pid, describe) or
    None; `on_kill(pid, why)` is notified after a SIGKILL."""

    def __init__(self, *, threshold: float, period_s: float,
                 pick_victim: Callable, on_kill: Callable | None = None,
                 usage_fn: Callable[[], float] = host_memory_usage):
        self.threshold = threshold
        self.period_s = period_s
        self.pick_victim = pick_victim
        self.on_kill = on_kill
        self.usage_fn = usage_fn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self.kills = 0

    def start(self) -> "MemoryMonitor":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                usage = self.usage_fn()
                if usage <= self.threshold:
                    continue
                victim = self.pick_victim()
                if victim is None:
                    continue
                pid, desc = victim
                why = (f"host memory usage {usage:.0%} exceeded the "
                       f"{self.threshold:.0%} threshold; killed {desc} "
                       f"(rss {proc_rss_bytes(pid) / 1e6:.0f} MB) to protect "
                       f"the node")
                # record the reason BEFORE the kill: death detection races
                # the callback otherwise and the task error loses its cause
                if self.on_kill is not None:
                    self.on_kill(pid, why)
                try:
                    os.kill(pid, 9)
                except (ProcessLookupError, PermissionError):
                    if self.on_kill is not None:
                        self.on_kill(pid, None)  # kill failed: clear it
                    continue
                self.kills += 1
                logger.warning(why)
                # give the death bookkeeping a beat before re-evaluating
                time.sleep(min(1.0, self.period_s * 2))
            except Exception:  # noqa: BLE001 — the monitor must survive
                logger.exception("memory monitor iteration failed")
