"""Standalone monitor process: the autoscaler loop as its own OS process.

(reference: python/ray/autoscaler/_private/monitor.py — the head node runs
`monitor.py` as a separate process that connects to the GCS, reads demand,
and drives the NodeProvider; the control plane never blocks on cloud API
calls. Here the same Autoscaler class the in-process tests use is hosted
behind a CLI entry; `ray_tpu start --head --autoscaling-config=...`
launches it, or run `python -m ray_tpu._private.monitor` by hand.)

Config (JSON or YAML):
    provider:
      type: local | gce_tpu | kuberay        # fake_gce_tpu for tests
      ... provider-specific keys ...
    node_types:
      worker: {resources: {CPU: 4}, min_nodes: 0, max_nodes: 10}
    idle_timeout_s: 60
    interval_s: 2
"""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading

logger = logging.getLogger(__name__)


def load_config(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except ValueError:
        import yaml

        return yaml.safe_load(text)


def build_provider(cfg: dict, gcs_address: str):
    p = dict(cfg.get("provider") or {"type": "local"})
    kind = p.pop("type", "local")
    if kind == "local":
        from ray_tpu.autoscaler.node_provider import LocalNodeProvider

        return LocalNodeProvider(gcs_address)
    if kind == "gce_tpu":
        from ray_tpu.autoscaler.gce_rest import RestGceTpuApi
        from ray_tpu.autoscaler.gce_tpu import GceTpuNodeProvider

        # fail LOUDLY here, at monitor/`ray_tpu start` time — a missing
        # project/zone or unusable credentials must not wait for the first
        # scale-up to surface (VERDICT r4 weak #8)
        missing = [k for k in ("project", "zone") if not p.get(k)]
        if missing:
            raise ValueError(
                f"gce_tpu provider config is missing {missing}: the REST "
                "client cannot target tpu.googleapis.com without them "
                "(autoscaling-config provider: {type: gce_tpu, project: "
                "..., zone: ...})")
        api_kw = {k: p.pop(k) for k in ("runtime_version", "network",
                                        "preemptible") if k in p}
        api = RestGceTpuApi(project=p.pop("project"), zone=p.pop("zone"),
                            gcs_address=gcs_address, **api_kw)
        api.validate()
        return GceTpuNodeProvider(api, **p)
    if kind == "fake_file":
        # file-backed fake "cloud" with SIGKILL fault injection — the
        # provider crash-restart chaos tests drive the real monitor process
        # through it (tests/test_autoscaler_chaos.py)
        from ray_tpu.autoscaler.node_provider import FakeFileNodeProvider

        return FakeFileNodeProvider(
            p.pop("path"),
            die_after_create=int(p.pop("die_after_create", 0)))
    if kind == "fake_gce_tpu":
        from ray_tpu.autoscaler.gce_tpu import (FakeGceTpuApi,
                                                GceTpuNodeProvider)

        return GceTpuNodeProvider(FakeGceTpuApi(), **p)
    if kind == "kuberay":
        from ray_tpu.autoscaler.kuberay import (KubeRayApiClient,
                                                KubeRayNodeProvider)

        api = KubeRayApiClient(p.pop("namespace"), p.pop("cluster_name"),
                               **{k: p.pop(k) for k in ("api_server", "token")
                                  if k in p})
        return KubeRayNodeProvider(api, **p)
    raise ValueError(f"unknown provider type {kind!r}")


def build_node_types(cfg: dict):
    from ray_tpu.autoscaler.autoscaler import NodeType

    out = []
    for name, spec in (cfg.get("node_types") or {}).items():
        out.append(NodeType(
            name=name, resources=dict(spec.get("resources") or {}),
            labels=dict(spec.get("labels") or {}),
            min_nodes=int(spec.get("min_nodes", 0)),
            max_nodes=int(spec.get("max_nodes", 10))))
    if not out:
        raise ValueError("autoscaling config has no node_types")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu monitor")
    ap.add_argument("--address", required=True,
                    help="GCS address host:port or unix:<path>")
    ap.add_argument("--autoscaling-config", required=True)
    ap.add_argument("--keep-nodes-on-exit", action="store_true",
                    help="leave provider nodes running when the monitor "
                         "process is stopped")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s monitor %(levelname)s %(message)s")
    cfg = load_config(args.autoscaling_config)
    provider = build_provider(cfg, args.address)
    from ray_tpu.autoscaler.autoscaler import Autoscaler

    scaler = Autoscaler(
        args.address, provider, build_node_types(cfg),
        interval_s=float(cfg.get("interval_s", 2.0)),
        idle_timeout_s=float(cfg.get("idle_timeout_s", 60.0)),
        node_startup_grace_s=float(cfg.get("node_startup_grace_s", 60.0)))
    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    logger.info("monitor up: %s provider, %d node types",
                type(provider).__name__, len(scaler.node_types))
    from ray_tpu._private.protocol import ConnectionClosed

    while not stop.is_set():
        try:
            scaler.reconcile_once()
        except ConnectionClosed:
            # the head/GCS is gone: exit instead of looping forever as an
            # orphan keeping cloud nodes alive against a dead cluster
            logger.warning("GCS connection closed; monitor exiting")
            break
        except Exception:
            logger.exception("reconcile failed")
        stop.wait(scaler.interval_s)
    scaler.stop(terminate_nodes=not args.keep_nodes_on_exit)
    logger.info("monitor stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
