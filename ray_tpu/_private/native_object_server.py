"""ctypes binding for the native object-plane server (cpp/object_server.cc).

The server streams sealed store files (tmpfs or spill tier) to other hosts
with zero Python on the hot path — the C++ counterpart of the reference's
object manager transfer plane (reference:
src/ray/object_manager/object_manager.h:128). Selected with
RAY_TPU_OBJECT_SERVER_BACKEND=native; its addresses carry a "native:"
prefix so fetchers pick the binary codec per remote host.

Wire format (binary, little-endian):
  request:  [u32 oid_len][oid]
  response: [u64 size][payload]          (size == 2^64-1 → not found)
"""

from __future__ import annotations

import ctypes
import os
import socket
import struct
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "object_server.cc")
_LIB = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "build",
                    "libobjserver.so")
_NOT_FOUND = (1 << 64) - 1

_build_lock = threading.Lock()
_lib = None


def _ensure_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src, lib = os.path.abspath(_SRC), os.path.abspath(_LIB)
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            os.makedirs(os.path.dirname(lib), exist_ok=True)
            tmp = lib + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src, "-lpthread"],
                check=True, capture_output=True)
            os.replace(tmp, lib)
        dll = ctypes.CDLL(lib)
        dll.objsrv_start.restype = ctypes.c_void_p
        dll.objsrv_start.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                     ctypes.c_char_p, ctypes.c_int]
        dll.objsrv_port.restype = ctypes.c_int
        dll.objsrv_port.argtypes = [ctypes.c_void_p]
        dll.objsrv_stop.argtypes = [ctypes.c_void_p]
        _lib = dll
        return dll


class NativeObjectServer:
    """Drop-in for ObjectPlaneServer when the store is file-backed."""

    def __init__(self, store, host: str | None = None):
        from ray_tpu._private.object_store import ShmObjectStore
        from ray_tpu._private.ray_config import RayConfig

        if not isinstance(store, ShmObjectStore):
            raise ValueError(
                "the native object server serves file-backed stores; the "
                "arena backend keeps its own layout (use the python server)")
        from ray_tpu._private.object_store import SHM_DIR

        self.bind_host = host or RayConfig.get("bind_host")
        self._dll = _ensure_lib()
        prefix = os.path.join(SHM_DIR, store.prefix)
        self._handle = self._dll.objsrv_start(
            prefix.encode(), store.spill_dir.encode(),
            self.bind_host.encode(), 0)
        if not self._handle:
            raise OSError("native object server failed to start")
        self.port = self._dll.objsrv_port(self._handle)

    @property
    def address(self) -> str:
        from ray_tpu._private.object_transfer import _local_ip

        host = _local_ip() if self.bind_host == "0.0.0.0" else self.bind_host
        return f"native:{host}:{self.port}"

    def stop(self) -> None:
        if self._handle:
            self._dll.objsrv_stop(self._handle)
            self._handle = None


def fetch_native(store, oid: str, host: str, port: int,
                 timeout: float = 60.0) -> "str | bool":
    """Client side of the binary protocol: pull one object into `store`.
    Returns the landing tier, or False on miss/error."""
    try:
        with socket.create_connection((host, port), timeout=timeout) as sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            raw = oid.encode()
            sock.sendall(struct.pack("<I", len(raw)) + raw)
            head = _recv_exact(sock, 8)
            if head is None:
                return False
            (size,) = struct.unpack("<Q", head)
            if size == _NOT_FOUND:
                return False
            parts = []
            got = 0
            while got < size:
                chunk = sock.recv(min(1 << 20, size - got))
                if not chunk:
                    return False
                parts.append(chunk)
                got += len(chunk)
        return store.put_parts(oid, parts, size) or "shm"
    except OSError:
        return False


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf
