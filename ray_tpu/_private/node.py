"""Node: session bootstrap — starts the GCS and owns the worker pool.

(reference: python/ray/_private/node.py:47 starts gcs/raylet/log-monitor
subprocesses; here the GCS runs as an in-process thread and workers are
subprocesses. Multi-node: a follower node will run a thin agent that connects
its worker pool to a remote GCS over TCP — message types are already
node-agnostic.)
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid

from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.object_store import ShmObjectStore


def detect_num_tpu_chips() -> int:
    """TPU chip count without importing jax (reference:
    python/ray/_private/accelerators/tpu.py:100 chips-per-host logic — there
    via GKE env vars / GCE metadata; here via env override or device files)."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env:
        return int(env)
    try:
        import glob

        accel = glob.glob("/dev/accel*") + glob.glob("/dev/vfio/*")
        if accel:
            return len(accel)
    except OSError:
        pass
    return 0


class Node:
    def __init__(
        self,
        *,
        resources: dict | None = None,
        num_cpus: float | None = None,
        num_tpus: float | None = None,
        num_workers: int = 0,
        max_workers: int = 16,
        session_dir: str | None = None,
        labels: dict | None = None,
    ):
        self.session_id = uuid.uuid4().hex[:8]
        base = session_dir or os.path.join("/tmp", "ray_tpu")
        self.session_dir = os.path.join(base, f"session_{self.session_id}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.socket_path = os.path.join(self.session_dir, "gcs.sock")

        total = {"CPU": float(num_cpus if num_cpus is not None else (os.cpu_count() or 1))}
        ntpu = num_tpus if num_tpus is not None else detect_num_tpu_chips()
        if ntpu:
            total["TPU"] = float(ntpu)
        if resources:
            total.update({k: float(v) for k, v in resources.items()})
        self.total_resources = total

        self._procs: list[subprocess.Popen] = []
        self._spawn_lock = threading.Lock()
        self.gcs = GcsServer(
            self.socket_path,
            total_resources=total,
            spawn_worker_cb=self._spawn_workers,
            max_workers=max_workers,
            node_labels=labels,
        )
        self.gcs.start()
        # wait for socket
        for _ in range(500):
            if os.path.exists(self.socket_path):
                break
            time.sleep(0.005)
        if num_workers:
            now = time.monotonic()
            # counted before spawn to avoid a register race
            self.gcs._spawn_pending["node-0"].extend([now] * num_workers)
            self._spawn_workers(num_workers, "node-0")

    def _spawn_workers(self, n: int, node_id: str = "node-0"):
        env = dict(os.environ)
        env["RAY_TPU_SOCKET"] = self.socket_path
        env["RAY_TPU_SESSION"] = self.session_id
        env["RAY_TPU_NODE_ID"] = node_id
        # Workers run CPU jax: the driver owns the TPU chip(s). Hard-set (not
        # setdefault) because the host env may preset JAX_PLATFORMS to the TPU
        # platform, and two processes must not fight over one chip
        # (reference: TPU_VISIBLE_CHIPS isolation, _private/accelerators/tpu.py:36).
        platform = os.environ.get("RAY_TPU_WORKER_PLATFORM", "cpu")
        env["JAX_PLATFORMS"] = platform
        if platform == "cpu":
            # CPU workers must not register a TPU-plugin session at interpreter
            # start (sitecustomize triggers on this env var): the per-process
            # registration dials the device-pool relay, and a worker blocking
            # on (or wedging) the single-chip grant takes the whole pool down.
            env.pop("PALLAS_AXON_POOL_IPS", None)
        with self._spawn_lock:
            for _ in range(n):
                log = open(os.path.join(self.session_dir, "logs", f"worker-{len(self._procs)}.log"), "ab")
                try:
                    p = subprocess.Popen(
                        [sys.executable, "-m", "ray_tpu._private.worker_main"],
                        env=env,
                        stdout=log,
                        stderr=subprocess.STDOUT,
                        cwd=os.getcwd(),
                    )
                finally:
                    log.close()  # Popen dup'd the fd; parent copy would leak
                self._procs.append(p)

    def shutdown(self):
        self.gcs.stop()
        deadline = time.monotonic() + 3.0
        for p in self._procs:
            try:
                p.wait(timeout=max(0.05, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        ShmObjectStore(self.session_id).cleanup_session()
