"""Node: session bootstrap — starts the GCS and owns the worker pool.

(reference: python/ray/_private/node.py:47 starts gcs/raylet/log-monitor
subprocesses; here the GCS runs as an in-process thread and workers are
subprocesses. Multi-node: a follower node will run a thin agent that connects
its worker pool to a remote GCS over TCP — message types are already
node-agnostic.)
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import uuid

from ray_tpu._private import accelerators
from ray_tpu._private.accelerators import detect_num_tpu_chips  # noqa: F401 (re-export)
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.ray_config import RayConfig
from ray_tpu._private.object_store import make_object_store
from ray_tpu._private.procutil import drain_procs


class Node:
    def __init__(
        self,
        *,
        resources: dict | None = None,
        num_cpus: float | None = None,
        num_tpus: float | None = None,
        num_workers: int = 0,
        max_workers: int = 16,
        session_dir: str | None = None,
        labels: dict | None = None,
    ):
        self.session_id = uuid.uuid4().hex[:8]
        base = session_dir or os.path.join("/tmp", "ray_tpu")
        self.session_dir = os.path.join(base, f"session_{self.session_id}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.socket_path = os.path.join(self.session_dir, "gcs.sock")

        total, labels = accelerators.detect_host_resources(
            num_cpus, num_tpus, resources, labels)
        self.total_resources = total
        self.node_labels = labels

        self._procs: list[subprocess.Popen] = []
        self._spawn_lock = threading.Lock()
        # per-host runtime-env agent process, started on first pip/conda
        # worker spawn (reference: _private/runtime_env/agent/ — a separate
        # process builds envs, deduplicating concurrent requests)
        from ray_tpu._private.runtime_env_agent import AgentHandle

        self._renv_agent = AgentHandle(self.session_dir)
        self.gcs = GcsServer(
            self.socket_path,
            total_resources=total,
            spawn_worker_cb=self._spawn_workers,
            max_workers=max_workers,
            node_labels=labels,
            session_id=self.session_id,
        )
        self.gcs.start()
        # the head host's object-plane server: follower hosts pull shm
        # objects from here (and vice versa) over chunked TCP
        from ray_tpu._private.object_transfer import make_object_server

        self.object_server = make_object_server(make_object_store(self.session_id))
        self.gcs.set_head_object_addr(self.object_server.address)
        # cross-host control-plane address (follower agents, remote drivers)
        self.address = f"127.0.0.1:{self.gcs.tcp_port}"
        # stream worker logs to the driver's stderr (reference:
        # _private/log_monitor.py); RAY_TPU_LOG_TO_DRIVER=0 disables
        self.log_monitor = None
        if RayConfig.get("log_to_driver"):
            from ray_tpu._private.log_monitor import LogMonitor

            self.log_monitor = LogMonitor(
                os.path.join(self.session_dir, "logs")).start()
        # wait for socket
        for _ in range(500):
            if os.path.exists(self.socket_path):
                break
            time.sleep(0.005)
        if num_workers:
            now = time.monotonic()
            # counted before spawn to avoid a register race
            self.gcs._spawn_pending["node-0"].extend([(now, None, "")] * num_workers)
            self._spawn_workers(num_workers, "node-0")

    def _spawn_workers(self, n: int, node_id: str = "node-0", chip_assignments=None,
                       runtime_env: dict | None = None):
        """Spawn n workers; chip_assignments[i] is a tuple of chip ids (the
        worker owns those chips via TPU_VISIBLE_CHIPS and runs real-TPU jax)
        or None (plain CPU worker). `runtime_env` is a normalized runtime
        env baked into the processes (env_vars at spawn; packages
        materialized by worker_main)."""
        import json as _json

        base = dict(os.environ)
        base["RAY_TPU_SOCKET"] = self.socket_path
        base["RAY_TPU_SESSION"] = self.session_id
        base["RAY_TPU_NODE_ID"] = node_id
        if runtime_env:
            base["RAY_TPU_RUNTIME_ENV"] = _json.dumps(runtime_env, sort_keys=True)
            base.update(runtime_env.get("env_vars") or {})
            if runtime_env.get("pip") or runtime_env.get("conda"):
                # env-bearing workers resolve their interpreter through the
                # per-host runtime-env agent (deduped builds, fail-fast);
                # the boot shim falls back to a local build if it's gone
                try:
                    base["RAY_TPU_RENV_AGENT_SOCK"] = self._renv_agent.ensure()
                except Exception:
                    pass
        else:
            base.pop("RAY_TPU_RUNTIME_ENV", None)
        with self._spawn_lock:
            for i in range(n):
                chips = chip_assignments[i] if chip_assignments else None
                env = dict(base)
                if chips:
                    # chip worker: keep the host's TPU platform env (incl.
                    # device-pool vars) and restrict it to its chip subset
                    # before any jax import in the child
                    # (reference: TPU_VISIBLE_CHIPS, accelerators/tpu.py:36)
                    accelerators.apply_chip_env(env, chips)
                else:
                    # CPU workers must not own the chip: hard-set (not
                    # setdefault) because the host env may preset
                    # JAX_PLATFORMS to the TPU platform, and two processes
                    # must not fight over one chip.
                    platform = RayConfig.get("worker_platform")
                    env["JAX_PLATFORMS"] = platform
                    if platform == "cpu":
                        # CPU workers must not register a TPU-plugin session
                        # at interpreter start (sitecustomize triggers on this
                        # env var): the per-process registration dials the
                        # device-pool relay, and a worker blocking on (or
                        # wedging) the single-chip grant takes the pool down.
                        env.pop("PALLAS_AXON_POOL_IPS", None)
                # pip runtime envs boot through a shim that builds the venv
                # IN the worker process, then re-execs under its interpreter
                # — the scheduler thread never waits on pip
                from ray_tpu._private.runtime_env_container import (
                    boot_entry, build_worker_argv)

                argv = build_worker_argv(runtime_env, env, self.session_dir,
                                         boot_entry(runtime_env))
                log = open(os.path.join(self.session_dir, "logs", f"worker-{len(self._procs)}.log"), "ab")
                try:
                    p = subprocess.Popen(
                        argv,
                        env=env,
                        stdout=log,
                        stderr=subprocess.STDOUT,
                        cwd=os.getcwd(),
                    )
                finally:
                    log.close()  # Popen dup'd the fd; parent copy would leak
                self._procs.append(p)

    def restart_gcs(self) -> None:
        """Stand up a fresh GCS on the same socket after a (simulated) crash,
        rebuilding from persistent storage (reference: GCS restart with
        external Redis — gcs_init_data.h rebuild; clients reconnect via
        retryable channels). The old GCS must already be stopped/crashed."""
        self.gcs = GcsServer(
            self.socket_path,
            total_resources=self.total_resources,
            spawn_worker_cb=self._spawn_workers,
            max_workers=self.gcs.max_workers,
            node_labels=self.node_labels,
            session_id=self.session_id,
        )
        self.gcs.start()
        self.gcs.set_head_object_addr(self.object_server.address)
        self.address = f"127.0.0.1:{self.gcs.tcp_port}"

    def shutdown(self):
        if self.log_monitor is not None:
            self.log_monitor.stop()
        self._renv_agent.stop()
        self.object_server.stop()
        self.gcs.stop()
        drain_procs(self._procs)
        # backend-aware teardown: the arena backend must also unlink its
        # /dev/shm segment and spill dir, not just per-object files
        make_object_store(self.session_id).cleanup_session()
