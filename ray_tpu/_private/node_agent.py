"""Follower-host agent: joins a remote GCS and hosts a local worker pool.

`python -m ray_tpu._private.node_agent --address <gcs host:port> [...]`

Plays the reference raylet's cluster-facing role on a non-head machine:
registers the host and its resources with the GCS over TCP, spawns worker
processes on demand when the GCS asks, runs the host's object-plane server
(chunked TCP pulls from the local shm store), and forwards worker log lines
to the GCS for driver-side streaming
(reference capability: raylet registration gcs_node_manager.h:47 + worker
pool worker_pool.h:280 + object manager object_manager.h:128 + log monitor
_private/log_monitor.py, collapsed into one agent process).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
import uuid

from ray_tpu._private import accelerators
from ray_tpu._private.log_monitor import LogMonitor
from ray_tpu._private.object_store import make_object_store
from ray_tpu._private.object_transfer import make_object_server
from ray_tpu._private.procutil import drain_procs
from ray_tpu._private.protocol import ConnectionClosed, connect_address


class NodeAgent:
    def __init__(self, *, address: str, host_id: str | None = None,
                 num_cpus: float | None = None, num_tpus: float | None = None,
                 resources: dict | None = None, labels: dict | None = None,
                 session_dir: str | None = None):
        self.gcs_address = address
        self.host_id = host_id or f"host-{uuid.uuid4().hex[:8]}"
        self.conn = connect_address(address)
        self._rid = 1

        # handshake: learn the session id before anything store-related
        hello = self._rpc({"type": "get_session"})
        self.session_id = hello["session_id"]

        # this host's own shm namespace (a real second machine gets this for
        # free; on one machine the namespace keeps the stores honest-disjoint)
        self.store_ns = f"{self.session_id}_{self.host_id}"
        self.store = make_object_store(self.store_ns)
        if hasattr(self.store, "on_evict"):
            # arena backend: local evict-to-spill must reach the GCS
            # accountant, same as the head workers' hook
            self.store.on_evict = self._report_evictions
        self.obj_server = make_object_server(self.store)

        base = session_dir or os.path.join("/tmp", "ray_tpu")
        self.session_dir = os.path.join(
            base, f"session_{self.session_id}", f"agent_{self.host_id}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)

        total, labels = accelerators.detect_host_resources(
            num_cpus, num_tpus, resources, labels)

        from ray_tpu._private.runtime_env_agent import AgentHandle

        self._renv_agent = AgentHandle(self.session_dir)
        self._procs: list[subprocess.Popen] = []
        self._rpc({
            "type": "register_host",
            "host_id": self.host_id,
            "node_id": self.host_id,  # one vnode per follower host
            "resources": total,
            "labels": labels,
            "object_addr": self.obj_server.address,
        })
        self.log_monitor = LogMonitor(
            os.path.join(self.session_dir, "logs"), sink=self._forward_log).start()
        # OOM defense for THIS host. Victim choice is delegated to the GCS
        # (same policy as the head: newest retriable plain task first, never
        # actors), since only it knows what each pid runs (reference:
        # per-raylet memory monitor, memory_monitor.h:52 + group-by-owner
        # policy). A dedicated query connection keeps the monitor thread off
        # the agent's main dispatch socket.
        self.mem_monitor = None
        from ray_tpu._private.ray_config import RayConfig
        refresh_ms = RayConfig.get("memory_monitor_refresh_ms")
        if refresh_ms > 0:
            from ray_tpu._private.memory_monitor import MemoryMonitor

            state = {"conn": None, "rid": 0}

            def _clear(pid):
                """Un-tag a declined/failed victim so an unrelated later
                death isn't misattributed to memory pressure."""
                try:
                    if state["conn"] is not None:
                        state["conn"].send({"type": "oom_clear",
                                            "host_id": self.host_id,
                                            "pid": pid})
                except (ConnectionClosed, OSError):
                    state["conn"] = None

            def pick():
                try:
                    if state["conn"] is None:
                        state["conn"] = connect_address(self.gcs_address)
                        # a hung GCS must not wedge the monitor forever —
                        # the kernel OOM killer is what we're racing
                        state["conn"].sock.settimeout(5.0)
                    state["rid"] += 1
                    state["conn"].send({
                        "type": "pick_oom_victim", "rid": state["rid"],
                        "host_id": self.host_id,
                        "why": f"host {self.host_id} memory pressure"})
                    while True:
                        reply = state["conn"].recv()
                        if reply.get("rid") == state["rid"]:
                            break
                except (ConnectionClosed, OSError):
                    state["conn"] = None
                    return None
                pid = reply.get("pid")
                if pid is None:
                    return None
                # only kill pids this agent actually spawned
                if not any(p.pid == pid and p.poll() is None
                           for p in self._procs):
                    _clear(pid)
                    return None
                return pid, f"worker pid {pid} on host {self.host_id}"

            def on_kill(pid, why):
                if why is None:  # the SIGKILL itself failed
                    _clear(pid)

            self.mem_monitor = MemoryMonitor(
                threshold=RayConfig.get("memory_usage_threshold"),
                period_s=refresh_ms / 1000.0, pick_victim=pick,
                on_kill=on_kill).start()

    def _rpc(self, msg: dict) -> dict:
        msg["rid"] = self._rid
        self._rid += 1
        self.conn.send(msg)
        while True:
            reply = self.conn.recv()
            if reply.get("rid") == msg["rid"]:
                return reply
            self._dispatch(reply)

    def _report_evictions(self, oids: list) -> None:
        try:
            self.conn.send({"type": "objects_evicted",
                            "host": self.host_id, "oids": list(oids)})
        except ConnectionClosed:
            pass

    def _forward_log(self, source: str, line: str):
        try:
            self.conn.send({"type": "log_line",
                            "source": f"{self.host_id}/{source}", "line": line})
        except ConnectionClosed:
            pass

    def _resource_view(self) -> dict:
        """One periodic resource-view delta (reference: ray_syncer's
        RESOURCE_VIEW channel — raylets broadcast their load so the rest
        of the cluster schedules on fresh state, syncer.h). Here the view
        feeds the GCS host table, the state API and the dashboard."""
        from ray_tpu._private.memory_monitor import host_memory_usage

        try:
            load1 = os.getloadavg()[0]
        except OSError:
            load1 = 0.0
        live = sum(1 for p in self._procs if p.poll() is None)
        return {"type": "resource_view", "host_id": self.host_id,
                "mem_usage": round(host_memory_usage(), 4),
                "load1": round(load1, 2), "num_worker_procs": live}

    def _view_loop(self, period_s: float):
        while not self._stopping:
            time.sleep(period_s)
            try:
                self.conn.send(self._resource_view())
            except ConnectionClosed:
                return

    def serve_forever(self):
        from ray_tpu._private.ray_config import RayConfig

        period = RayConfig.get("resource_view_interval_s")
        self._stopping = False
        if period > 0:
            threading.Thread(target=self._view_loop, args=(period,),
                             daemon=True, name="agent-view").start()
        try:
            while True:
                self._dispatch(self.conn.recv())
        except ConnectionClosed:
            pass
        finally:
            self._stopping = True
            self.shutdown()

    def _dispatch(self, msg: dict):
        t = msg.get("type")
        if t == "spawn_workers":
            self._spawn_workers(msg["assignments"], msg.get("node_id", self.host_id),
                                msg.get("runtime_env"))
        elif t == "delete_objects":
            for oid in msg["oids"]:
                try:
                    self.store.delete(oid)
                except Exception:
                    pass
        elif t == "spill_objects":
            for oid in msg["oids"]:
                try:
                    self.store.spill(oid)
                except Exception:
                    pass
        elif t == "ping":
            # GCS active health check (reference: gcs_health_check_manager.h)
            try:
                self.conn.send({"type": "pong", "host_id": self.host_id})
            except ConnectionClosed:
                pass
        elif t == "drain_notice":
            # the GCS already fanned the notice out to resident workers
            # (they connect to it directly); the agent just logs it and
            # keeps serving through the grace window
            print(f"node agent {self.host_id}: node {msg.get('node_id')} "
                  f"draining ({msg.get('reason')}), grace "
                  f"{msg.get('grace_s')}s", flush=True)
        elif t == "exit":
            raise ConnectionClosed()

    def self_drain(self, reason: str) -> None:
        """Ask the GCS to drain this host's node (SIGTERM / preemption
        notice path). Runs on a dedicated connection so it cannot interleave
        with the main dispatch socket's request/reply traffic."""
        from ray_tpu._private.ray_config import RayConfig

        try:
            conn = connect_address(self.gcs_address)
            conn.send({"type": "node_drain", "rid": 1,
                       "node_id": self.host_id,
                       "grace_s": RayConfig.get("drain_grace_s"),
                       "reason": reason})
            reply = conn.recv()
            print(f"node agent {self.host_id}: self-drain ({reason}) → "
                  f"{reply}", flush=True)
            conn.close()
        except (ConnectionClosed, OSError) as e:
            print(f"node agent {self.host_id}: self-drain failed: {e}",
                  flush=True)

    def _spawn_workers(self, assignments: list, node_id: str,
                       runtime_env: dict | None = None):
        import json as _json

        base = dict(os.environ)
        base["RAY_TPU_ADDRESS"] = self.gcs_address
        base["RAY_TPU_SESSION"] = self.session_id
        base["RAY_TPU_NODE_ID"] = node_id
        base["RAY_TPU_HOST_ID"] = self.host_id
        base["RAY_TPU_STORE_NS"] = self.store_ns
        if runtime_env:
            base["RAY_TPU_RUNTIME_ENV"] = _json.dumps(runtime_env, sort_keys=True)
            base.update(runtime_env.get("env_vars") or {})
            if runtime_env.get("pip") or runtime_env.get("conda"):
                try:
                    base["RAY_TPU_RENV_AGENT_SOCK"] = self._renv_agent.ensure()
                except Exception:
                    pass
        else:
            base.pop("RAY_TPU_RUNTIME_ENV", None)
        for chips in assignments:
            env = dict(base)
            if chips:
                accelerators.apply_chip_env(env, chips)
            else:
                from ray_tpu._private.ray_config import RayConfig
                platform = RayConfig.get("worker_platform")
                env["JAX_PLATFORMS"] = platform
                if platform == "cpu":
                    env.pop("PALLAS_AXON_POOL_IPS", None)
            from ray_tpu._private.runtime_env_container import (
                boot_entry, build_worker_argv)

            argv = build_worker_argv(runtime_env, env, self.session_dir,
                                     boot_entry(runtime_env))
            log = open(os.path.join(self.session_dir, "logs",
                                    f"worker-{len(self._procs)}.log"), "ab")
            try:
                p = subprocess.Popen(
                    argv,
                    env=env, stdout=log, stderr=subprocess.STDOUT,
                    cwd=os.getcwd())
            finally:
                log.close()
            self._procs.append(p)

    def shutdown(self):
        if self.mem_monitor is not None:
            self.mem_monitor.stop()
        self._renv_agent.stop()
        self.log_monitor.stop()
        self.obj_server.stop()
        drain_procs(self._procs)
        if hasattr(self.store, "release_pid_pins"):
            try:
                self.store.release_pid_pins()
            except Exception:
                pass
        self.store.cleanup_session()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--address", required=True, help="GCS address host:port or unix:<path>")
    p.add_argument("--host-id", default=None)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    args = p.parse_args(argv)
    agent = NodeAgent(address=args.address, host_id=args.host_id,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus)

    # GCE preemption delivers SIGTERM ahead of the instance kill: turn it
    # into a node drain so resident train workers grace-checkpoint. The
    # agent keeps serving; actual termination is the provider's (or the
    # autoscaler's) job after the grace window.
    import signal

    def _on_sigterm(signum, frame):
        threading.Thread(target=agent.self_drain, args=("SIGTERM",),
                         daemon=True, name="agent-self-drain").start()

    signal.signal(signal.SIGTERM, _on_sigterm)
    print(f"node agent {agent.host_id} joined {args.address} "
          f"(objects at {agent.obj_server.address})", flush=True)
    agent.serve_forever()


if __name__ == "__main__":
    main()
