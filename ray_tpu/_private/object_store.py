"""Per-node shared-memory object store (plasma equivalent).

Objects live as files under /dev/shm (tmpfs) and are mapped read-only by
consumers, giving zero-copy cross-process reads of numpy payloads the same way
the reference's plasma store hands out mmap'd fds
(reference: src/ray/object_manager/plasma/store.h, dlmalloc over mmap'd shm,
fd passing in fling.cc). Here tmpfs file names play the role of fds; the
optional C++ arena allocator (src/shm_alloc.cc) can back large stores with a
single mapped arena instead of one file per object.
"""

from __future__ import annotations

import logging
import mmap
import os
from typing import Iterable

from ray_tpu._private.constants import SHM_DIR  # noqa: F401 — re-exported
from ray_tpu._private.constants import SHM_SESSION_PREFIX

logger = logging.getLogger(__name__)


def make_object_store(session_id: str):
    """Backend selector: the default is the native C++ arena (one mmap'd
    segment, bounded capacity, LRU evict-to-spill — cpp/shm_store.cc);
    RAY_TPU_STORE_BACKEND=file selects one tmpfs file per object.

    A broken/missing toolchain (no g++, failed compile) degrades to the
    file backend with a warning instead of failing ray_tpu.init(). The
    choice is pinned into this process's environment so every child this
    host spawns inherits the SAME backend — processes of one session
    disagreeing on where objects live would strand every put."""
    from ray_tpu._private.ray_config import RayConfig

    if RayConfig.get("store_backend") == "arena":
        try:
            from ray_tpu._private import shm_arena

            # only the build/load step may degrade: a transient runtime
            # error constructing the store (fd exhaustion, bad mount) must
            # propagate — one process silently flipping backends mid-session
            # would strand every object it writes
            shm_arena._ensure_lib()
        except Exception as e:  # CalledProcessError / missing g++ / dlopen
            logger.warning(
                "native shm arena unavailable (%s: %s); falling back to the "
                "file object-store backend", type(e).__name__, e)
            os.environ["RAY_TPU_STORE_BACKEND"] = "file"
        else:
            return shm_arena.ArenaStore(session_id)
    return ShmObjectStore(session_id)


class PlasmaObject:
    """A sealed object: keeps the mmap alive while consumers hold views."""

    __slots__ = ("buf", "_mm", "_f")

    def __init__(self, buf: memoryview, mm=None, f=None):
        self.buf = buf
        self._mm = mm
        self._f = f


def spill_dir_for(ns: str) -> str:
    return os.path.join("/tmp", "ray_tpu", f"spill_{ns}")


class ShmObjectStore:
    """One store per session; all processes of the session share the prefix.

    Two tiers: tmpfs (hot, zero-copy) and a disk spill directory (cold).
    Reads fall back to the spill tier transparently; the GCS-driven spiller
    moves LRU objects down when the host's tmpfs budget is exceeded
    (reference: spill orchestration, raylet/local_object_manager.h:43)."""

    def __init__(self, session_id: str):
        self.prefix = f"{SHM_SESSION_PREFIX}{session_id}_"
        self.spill_dir = spill_dir_for(session_id)
        self._created: set[str] = set()

    def _path(self, object_hex: str) -> str:
        return os.path.join(SHM_DIR, self.prefix + object_hex)

    def _spill_path(self, object_hex: str) -> str:
        return os.path.join(self.spill_dir, object_hex)

    def put_parts(self, object_hex: str, parts: Iterable[bytes | memoryview], total: int) -> str:
        """Create+seal an object from pre-serialized parts. Returns the tier
        it actually landed on: "shm" (tmpfs) or "spill" (disk fallback) — so
        callers report true tmpfs usage to the GCS accountant."""
        path = self._path(object_hex)
        tmp = path + ".tmp"
        tier = "shm"
        try:
            self._write(tmp, path, parts, total)
        except OSError:  # tmpfs full: create straight into the spill tier
            try:
                os.unlink(tmp)  # don't strand a truncated file on full tmpfs
            except OSError:
                pass
            os.makedirs(self.spill_dir, exist_ok=True)
            spath = self._spill_path(object_hex)
            self._write(spath + ".tmp", spath, parts, total)
            tier = "spill"
        self._created.add(object_hex)
        return tier

    @staticmethod
    def _write(tmp: str, path: str, parts, total: int) -> None:
        # plain write(2), NOT an mmap store: tmpfs allocates lazily, so a
        # faulting mmap write on a full tmpfs raises SIGBUS (kills the
        # process) while write() returns ENOSPC — which the spill fallback
        # in put_parts can actually catch
        with open(tmp, "wb") as f:
            for p in parts:
                f.write(p)
        os.rename(tmp, path)  # atomic seal: readers never see partial objects

    def get(self, object_hex: str) -> PlasmaObject:
        try:
            f = open(self._path(object_hex), "rb")
        except FileNotFoundError:
            f = open(self._spill_path(object_hex), "rb")
        try:
            size = os.fstat(f.fileno()).st_size
            mm = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        except BaseException:
            # mmap raises on an empty/truncated file; the workers calling
            # get() under memory pressure are exactly the ones that cannot
            # afford to bleed one fd per failed read
            f.close()
            raise
        return PlasmaObject(memoryview(mm), mm, f)

    def contains(self, object_hex: str) -> bool:
        return (os.path.exists(self._path(object_hex))
                or os.path.exists(self._spill_path(object_hex)))

    def tier_of(self, object_hex: str) -> str | None:
        """Which tier holds the object right now ("shm" | "spill" | None)."""
        if os.path.exists(self._path(object_hex)):
            return "shm"
        if os.path.exists(self._spill_path(object_hex)):
            return "spill"
        return None

    def size(self, object_hex: str) -> int:
        try:
            return os.stat(self._path(object_hex)).st_size
        except FileNotFoundError:
            return os.stat(self._spill_path(object_hex)).st_size

    def spill(self, object_hex: str) -> bool:
        """Move an object from tmpfs to the disk tier (no-op if absent).
        tmp-copy + atomic replace: a crash mid-spill must never leave a
        truncated file where readers expect a sealed object."""
        src = self._path(object_hex)
        if not os.path.exists(src):
            return False
        os.makedirs(self.spill_dir, exist_ok=True)
        import shutil

        dst = self._spill_path(object_hex)
        tmp = dst + f".tmp{os.getpid()}"
        try:
            shutil.copyfile(src, tmp)
            os.replace(tmp, dst)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.unlink(src)
        return True

    def delete(self, object_hex: str) -> None:
        for path in (self._path(object_hex), self._spill_path(object_hex)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        self._created.discard(object_hex)

    def cleanup_session(self) -> None:
        """Unlink every object of this session (driver calls at shutdown)."""
        try:
            names = os.listdir(SHM_DIR)
        except FileNotFoundError:
            return
        for name in names:
            if name.startswith(self.prefix):
                try:
                    os.unlink(os.path.join(SHM_DIR, name))
                except OSError:
                    pass
        import shutil

        shutil.rmtree(self.spill_dir, ignore_errors=True)
