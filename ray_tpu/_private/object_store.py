"""Per-node shared-memory object store (plasma equivalent).

Objects live as files under /dev/shm (tmpfs) and are mapped read-only by
consumers, giving zero-copy cross-process reads of numpy payloads the same way
the reference's plasma store hands out mmap'd fds
(reference: src/ray/object_manager/plasma/store.h, dlmalloc over mmap'd shm,
fd passing in fling.cc). Here tmpfs file names play the role of fds; the
optional C++ arena allocator (src/shm_alloc.cc) can back large stores with a
single mapped arena instead of one file per object.
"""

from __future__ import annotations

import mmap
import os
from typing import Iterable

SHM_DIR = "/dev/shm"


def make_object_store(session_id: str):
    """Backend selector: RAY_TPU_STORE_BACKEND=arena uses the native C++
    arena (bounded capacity + LRU eviction, cpp/shm_store.cc); the default
    is one tmpfs file per object."""
    if os.environ.get("RAY_TPU_STORE_BACKEND") == "arena":
        from ray_tpu._private.shm_arena import ArenaStore

        return ArenaStore(session_id)
    return ShmObjectStore(session_id)


class PlasmaObject:
    """A sealed object: keeps the mmap alive while consumers hold views."""

    __slots__ = ("buf", "_mm", "_f")

    def __init__(self, buf: memoryview, mm=None, f=None):
        self.buf = buf
        self._mm = mm
        self._f = f


class ShmObjectStore:
    """One store per session; all processes of the session share the prefix."""

    def __init__(self, session_id: str):
        self.prefix = f"rtpu_{session_id}_"
        self._created: set[str] = set()

    def _path(self, object_hex: str) -> str:
        return os.path.join(SHM_DIR, self.prefix + object_hex)

    def put_parts(self, object_hex: str, parts: Iterable[bytes | memoryview], total: int) -> int:
        """Create+seal an object from pre-serialized parts. Returns size."""
        path = self._path(object_hex)
        tmp = path + ".tmp"
        with open(tmp, "w+b", buffering=0) as f:
            if total > 0:
                f.truncate(total)
            mm = mmap.mmap(f.fileno(), max(total, 1))
            off = 0
            for p in parts:
                n = len(p) if isinstance(p, bytes) else p.nbytes
                mm[off : off + n] = p
                off += n
            mm.flush()
            mm.close()
        os.rename(tmp, path)  # atomic seal: readers never see partial objects
        self._created.add(object_hex)
        return total

    def get(self, object_hex: str) -> PlasmaObject:
        path = self._path(object_hex)
        f = open(path, "rb")
        size = os.fstat(f.fileno()).st_size
        mm = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        return PlasmaObject(memoryview(mm), mm, f)

    def contains(self, object_hex: str) -> bool:
        return os.path.exists(self._path(object_hex))

    def size(self, object_hex: str) -> int:
        return os.stat(self._path(object_hex)).st_size

    def delete(self, object_hex: str) -> None:
        try:
            os.unlink(self._path(object_hex))
        except FileNotFoundError:
            pass
        self._created.discard(object_hex)

    def cleanup_session(self) -> None:
        """Unlink every object of this session (driver calls at shutdown)."""
        try:
            names = os.listdir(SHM_DIR)
        except FileNotFoundError:
            return
        for name in names:
            if name.startswith(self.prefix):
                try:
                    os.unlink(os.path.join(SHM_DIR, name))
                except OSError:
                    pass
