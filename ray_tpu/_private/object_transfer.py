"""Cross-host object plane: chunked pull of shm objects over TCP.

Every host of a session runs one `ObjectPlaneServer` in front of its local
store; a worker that needs a remote object dials the owning host's server,
streams the payload in chunks into its own store, seals it, and registers the
new copy with the GCS — pull-on-demand with per-object dedup, the same
semantics as the reference's node-to-node transfer plane
(reference: src/ray/object_manager/object_manager.h:128 — chunked push/pull,
default 5 MiB chunks; pull_manager.h:50 admission/dedup).
"""

from __future__ import annotations

import logging
import socket
import threading

from ray_tpu._private.protocol import (
    ConnectionClosed,
    MsgConnection,
    connect_tcp,
    listen_tcp,
)

logger = logging.getLogger(__name__)

from ray_tpu._private.ray_config import RayConfig as _RayConfig

CHUNK = _RayConfig.get("object_transfer_chunk")


def make_object_server(store, host: str | None = None):
    """Backend selector: RAY_TPU_OBJECT_SERVER_BACKEND=native runs the C++
    server (cpp/object_server.cc) for file-backed stores; default is the
    in-process Python server below."""
    from ray_tpu._private.ray_config import RayConfig

    if RayConfig.get("object_server_backend") == "native":
        from ray_tpu._private.native_object_server import NativeObjectServer
        from ray_tpu._private.object_store import ShmObjectStore

        if isinstance(store, ShmObjectStore):
            return NativeObjectServer(store, host)
        logger.warning("native object server needs the file store backend; "
                       "falling back to the python server")
    return ObjectPlaneServer(store, host)


class ObjectPlaneServer:
    """Serves local shm objects to other hosts. One thread per connection
    (an agent/worker keeps its connection open and pipelines fetches)."""

    def __init__(self, store, host: str | None = None):
        import os

        self.store = store
        # loopback by default; RAY_TPU_BIND_HOST=0.0.0.0 for real multi-host
        from ray_tpu._private.ray_config import RayConfig

        self.bind_host = host or RayConfig.get("bind_host")
        self.sock = listen_tcp(self.bind_host, 0)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="objsrv-accept")
        self._thread.start()

    @property
    def address(self) -> str:
        host = _local_ip() if self.bind_host == "0.0.0.0" else self.bind_host
        return f"{host}:{self.port}"

    def stop(self):
        # shutdown-not-close: freeing the fd while the accept thread may be
        # entering accept(2) lets a new listener reuse the fd number and
        # leak its connections to this stopped server (see GcsServer.stop)
        self._stop = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:  # wake accept() even where shutdown() on a listener doesn't
            s = socket.create_connection(("127.0.0.1", self.port), timeout=0.2)
            s.close()
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop:
            try:
                raw, _ = self.sock.accept()
            except OSError:
                break
            if self._stop:
                try:
                    raw.close()
                except OSError:
                    pass
                break
            conn = MsgConnection(raw)
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="objsrv-conn").start()
        try:
            self.sock.close()  # sole closer of the listener fd
        except OSError:
            pass

    def _serve(self, conn: MsgConnection):
        try:
            while True:
                msg = conn.recv()
                if msg.get("type") != "fetch":
                    conn.send({"ok": False, "error": f"bad request {msg.get('type')}"})
                    continue
                oid = msg["oid"]
                try:
                    obj = self.store.get(oid)
                except (FileNotFoundError, OSError):
                    conn.send({"ok": False, "error": "not found"})
                    continue
                try:
                    # chunked sends straight off the pinned view: the pin
                    # keeps the arena run alive under concurrent eviction,
                    # no staging copy of the whole object is ever made
                    buf = obj.buf
                    size = buf.nbytes if hasattr(buf, "nbytes") else len(buf)
                    conn.send({"ok": True, "size": size})
                    for off in range(0, size, CHUNK):
                        conn.send({"data": bytes(buf[off:off + CHUNK])})
                finally:
                    # arena objects pin until released (file objects GC with
                    # obj); release even on a broken send, or the pin leaks
                    # and wedges eviction for the whole session
                    if hasattr(obj, "release"):
                        obj.release()
        except ConnectionClosed:
            pass
        except Exception:
            logger.exception("object plane connection failed")
        finally:
            try:
                conn.close()
            except Exception:
                pass


class ObjectFetcher:
    """Per-process client side: cached connections, per-object in-flight
    dedup (two threads needing the same remote object fetch it once)."""

    def __init__(self, store):
        self.store = store
        self._conns: dict[str, MsgConnection] = {}
        self._lock = threading.Lock()
        self._inflight: dict[str, threading.Event] = {}
        # one request/response conversation per connection at a time — two
        # threads interleaving frames on one socket would cross-read payloads
        self._addr_locks: dict[str, threading.Lock] = {}

    def fetch(self, oid: str, address: str) -> "str | bool":
        """Pull `oid` from the object server at `address` into the local
        store. Returns the landing tier ("shm"/"spill") on a fresh pull,
        True when already/concurrently fetched, False on failure. Safe to
        call concurrently."""
        with self._lock:
            if self.store.contains(oid):
                return True
            ev = self._inflight.get(oid)
            if ev is None:
                self._inflight[oid] = ev = threading.Event()
                owner = True
            else:
                owner = False
        if not owner:
            ev.wait(timeout=300)
            return self.store.contains(oid)
        try:
            ok = self._fetch_once(oid, address)
        finally:
            ev.set()
            with self._lock:
                self._inflight.pop(oid, None)
        return ok

    def _fetch_once(self, oid: str, address: str) -> bool:
        with self._lock:
            alock = self._addr_locks.setdefault(address, threading.Lock())
        with alock:
            return self._fetch_conversation(oid, address)

    def _fetch_conversation(self, oid: str, address: str) -> bool:
        if address.startswith("native:"):
            # remote host runs the C++ server: binary codec, one connection
            # per fetch (the server is cheap-threaded; keep the client simple)
            from ray_tpu._private.native_object_server import fetch_native

            host, _, port = address[len("native:"):].rpartition(":")
            return fetch_native(self.store, oid, host or "127.0.0.1",
                                int(port))
        try:
            conn = self._conn(address)
            conn.send({"type": "fetch", "oid": oid})
            head = conn.recv()
            if not head.get("ok"):
                return False
            size = head["size"]
            parts = []
            got = 0
            while got < size:
                frame = conn.recv()
                data = frame["data"]
                parts.append(data)
                got += len(data)
            tier = self.store.put_parts(oid, parts, size)
            return tier or "shm"
        except (ConnectionClosed, OSError, KeyError):
            with self._lock:
                self._conns.pop(address, None)
            return False

    def _conn(self, address: str) -> MsgConnection:
        with self._lock:
            conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        host, _, port = address.rpartition(":")
        conn = connect_tcp(host, int(port), timeout=10.0)
        with self._lock:
            self._conns[address] = conn
        return conn


def _local_ip() -> str:
    """Best-effort routable IP of this host (falls back to loopback)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"
