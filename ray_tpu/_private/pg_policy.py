"""Bundle-placement policies for placement groups.

Pure functions: given the live nodes' free capacity (+ labels) and a bundle
list, return a per-bundle node assignment or None if unplaceable right now.

(reference: src/ray/gcs/gcs_placement_group_scheduler.h:281 +
raylet/scheduling/policy/bundle_scheduling_policy.h — STRICT_PACK / PACK /
STRICT_SPREAD / SPREAD. `SLICE` is our TPU-native addition: one bundle per
node of a single ICI-connected TPU slice, selected by node label, so a
worker group maps onto contiguous sub-tori — the reference approximates this
with the TPU-{pod_type}-head custom resource,
python/ray/_private/accelerators/tpu.py:170.)
"""

from __future__ import annotations

SLICE_LABEL = "ray_tpu.slice"

STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD", "SLICE")


def _fits(avail: dict, res: dict) -> bool:
    """Exact comparison — both sides must share one representation (the
    GCS passes fixed-point integer units on both; see fixed_point.py).
    The old float epsilon is gone: quantization makes it unnecessary."""
    return all(avail.get(k, 0) >= v for k, v in res.items())


def _deduct(avail: dict, res: dict) -> None:
    for k, v in res.items():
        avail[k] = avail.get(k, 0.0) - v


def _sum_bundles(bundles: list[dict]) -> dict:
    out: dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            out[k] = out.get(k, 0.0) + v
    return out


def _utilization(node) -> float:
    """Max over resources of used/total — the packing score."""
    score = 0.0
    for k, tot in node.total.items():
        if tot > 0:
            score = max(score, (tot - node.available.get(k, 0.0)) / tot)
    return score


def place_bundles(nodes: list, bundles: list[dict], strategy: str) -> list[str] | None:
    """Return [node_id per bundle] or None. Does not mutate node state."""
    if strategy not in STRATEGIES:
        raise ValueError(f"unknown placement strategy {strategy!r}")
    # DRAINING nodes are alive but scheduled around: running work drains
    # off; nothing new lands (reference: GCS DrainNode semantics)
    alive = [n for n in nodes if n.alive and not getattr(n, "draining", False)]
    if not alive:
        return None

    if strategy == "STRICT_PACK":
        need = _sum_bundles(bundles)
        for n in sorted(alive, key=_utilization):
            if _fits(n.available, need):
                return [n.node_id] * len(bundles)
        return None

    if strategy == "PACK":
        # best effort: try one node first, then first-fit over nodes by utilization
        need = _sum_bundles(bundles)
        for n in sorted(alive, key=_utilization):
            if _fits(n.available, need):
                return [n.node_id] * len(bundles)
        scratch = {n.node_id: dict(n.available) for n in alive}
        order = sorted(alive, key=_utilization)
        out = []
        for b in bundles:
            for n in order:
                if _fits(scratch[n.node_id], b):
                    _deduct(scratch[n.node_id], b)
                    out.append(n.node_id)
                    break
            else:
                return None
        return out

    if strategy == "STRICT_SPREAD":
        if len(bundles) > len(alive):
            return None
        scratch = {n.node_id: dict(n.available) for n in alive}
        used: set[str] = set()
        out = []
        for b in bundles:
            for n in sorted(alive, key=_utilization):
                if n.node_id not in used and _fits(scratch[n.node_id], b):
                    used.add(n.node_id)
                    out.append(n.node_id)
                    break
            else:
                return None
        return out

    if strategy == "SPREAD":
        scratch = {n.node_id: dict(n.available) for n in alive}
        loads = {n.node_id: _utilization(n) for n in alive}
        out = []
        for b in bundles:
            cands = sorted(alive, key=lambda n: (loads[n.node_id], n.node_id))
            for n in cands:
                if _fits(scratch[n.node_id], b):
                    _deduct(scratch[n.node_id], b)
                    loads[n.node_id] += 0.1  # nudge round-robin
                    out.append(n.node_id)
                    break
            else:
                return None
        return out

    # SLICE: one bundle per node, all nodes sharing one slice label value
    slices: dict[str, list] = {}
    for n in alive:
        lbl = n.labels.get(SLICE_LABEL)
        if lbl is not None:
            slices.setdefault(lbl, []).append(n)
    for lbl in sorted(slices):
        members = slices[lbl]
        if len(members) < len(bundles):
            continue
        scratch = {n.node_id: dict(n.available) for n in members}
        used: set[str] = set()
        out = []
        for b in bundles:
            for n in sorted(members, key=lambda n: n.node_id):
                if n.node_id not in used and _fits(scratch[n.node_id], b):
                    used.add(n.node_id)
                    _deduct(scratch[n.node_id], b)
                    out.append(n.node_id)
                    break
            else:
                break
        if len(out) == len(bundles):
            return out
    return None


def pick_node_hybrid(nodes: list, res: dict, local_node_id: str | None,
                     threshold: float | None = None) -> str | None:
    """Hybrid pack/spread for ordinary tasks: prefer the local node, pack onto
    low-utilization nodes until the threshold, then least-utilized first.
    (reference: raylet/scheduling/policy/scheduling_policy.h:66)"""
    if threshold is None:
        from ray_tpu._private.ray_config import RayConfig

        threshold = RayConfig.instance().hybrid_threshold
    alive = [n for n in nodes if n.alive and not getattr(n, "draining", False)]
    ordered = sorted(alive, key=lambda n: (n.node_id != local_node_id, n.node_id))
    for n in ordered:
        if _utilization(n) < threshold and _fits(n.available, res):
            return n.node_id
    fallback = sorted(alive, key=_utilization)
    for n in fallback:
        if _fits(n.available, res):
            return n.node_id
    return None
