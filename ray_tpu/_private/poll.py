"""Shared poll-with-backoff helper for host-plane rendezvous loops."""

from __future__ import annotations

import time
from typing import Any, Callable

_SLEEP_INIT = 0.0005
_SLEEP_CAP = 0.05


def poll_until(probe: Callable[[], Any], timeout: float | None, what: str) -> Any:
    """Call `probe` with exponential backoff until it returns non-None;
    raises TimeoutError(`what`) past `timeout` seconds (None = forever)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    sleep_s = _SLEEP_INIT
    while True:
        out = probe()
        if out is not None:
            return out
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(what)
        time.sleep(sleep_s)
        sleep_s = min(sleep_s * 2, _SLEEP_CAP)
