"""Child-process teardown shared by the head Node and follower NodeAgent."""

from __future__ import annotations

import subprocess
import time


def drain_procs(procs, deadline_s: float = 3.0, reap_timeout_s: float = 2.0):
    """Wait for `procs` to exit within a shared deadline, SIGKILL the rest,
    then reap the killed stragglers. The reap matters: SIGKILL is async, and
    a worker mid-boot that outlives the store teardown that follows would
    recreate the just-unlinked arena segment. Kill-all-then-reap keeps the
    worst case one reap round-trip, not `reap_timeout_s` per straggler."""
    deadline = time.monotonic() + deadline_s
    stragglers = []
    for p in procs:
        try:
            p.wait(timeout=max(0.05, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            stragglers.append(p)
    for p in stragglers:
        try:
            p.wait(timeout=reap_timeout_s)
        except subprocess.TimeoutExpired:
            pass
