"""Framed message protocol over unix-domain sockets.

Wire format: 8-byte little-endian length + pickled dict. Every message is a
dict with a "type" key; RPCs carry "rid" (request id) and replies mirror it.
This plays the role of the reference's gRPC + flatbuffers IPC planes
(reference: src/ray/rpc/grpc_server.h, src/ray/flatbuffers/node_manager.fbs)
collapsed into one socket protocol — adequate intra-node; a real RPC layer can
slot in per-message-type later without changing callers.
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time

_LEN = struct.Struct("<Q")
MAX_FRAME = 1 << 34


class ConnectionClosed(Exception):
    pass


class _Chaos:
    """Test-only fault injection, off unless env-configured (reference:
    src/ray/rpc/rpc_chaos.h:24, env RAY_testing_rpc_failure).

    RAY_TPU_TESTING_MSG_DROP="type_a,type_b:0.2" drops listed outbound
    message types with the given probability; RAY_TPU_TESTING_MSG_DELAY_MS=N
    sleeps up to N ms before every send (latency/reordering pressure).
    """

    def __init__(self):
        self.drop_types: set[str] = set()
        self.drop_prob = 0.0
        self.delay_ms = 0.0
        spec = os.environ.get("RAY_TPU_TESTING_MSG_DROP", "")
        if spec:
            types, _, prob = spec.partition(":")
            self.drop_types = {t for t in types.split(",") if t}
            self.drop_prob = float(prob or 0.1)
        self.delay_ms = float(os.environ.get("RAY_TPU_TESTING_MSG_DELAY_MS", "0") or 0)
        self.enabled = bool(self.drop_types or self.delay_ms)

    def intercept(self, msg: dict) -> bool:
        """True → drop the message."""
        if self.delay_ms:
            time.sleep(random.random() * self.delay_ms / 1000.0)
        return (msg.get("type") in self.drop_types
                and random.random() < self.drop_prob)


_chaos = _Chaos()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionClosed()
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


# language-neutral frame payload marker: "\0JSN" + UTF-8 JSON. Pickles
# can never start with a NUL byte (proto>=2 starts \x80; proto 0/1 with a
# printable opcode), so recv() can auto-detect the codec per frame —
# that's what lets non-Python workers (cpp/cpp_worker.cc) speak the same
# control plane.
_JSON_MAGIC = b"\x00JSN"


class MsgConnection:
    """Thread-safe framed connection; one reader, many writers."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()
        self.closed = False
        self.codec = "pickle"  # "json" for language-neutral peers

    def send(self, msg: dict) -> None:
        if _chaos.enabled and _chaos.intercept(msg):
            return  # injected drop
        if self.codec == "json":
            import json as _json

            data = _JSON_MAGIC + _json.dumps(msg).encode()
        else:
            data = pickle.dumps(msg, protocol=5)
        if len(data) > MAX_FRAME:
            raise ValueError(f"frame too large: {len(data)}")
        with self._send_lock:
            try:
                self.sock.sendall(_LEN.pack(len(data)) + data)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                self.closed = True
                raise ConnectionClosed() from e

    def recv(self) -> dict:
        try:
            header = _recv_exact(self.sock, 8)
            (n,) = _LEN.unpack(header)
            data = _recv_exact(self.sock, n)
        except (ConnectionResetError, OSError) as e:
            self.closed = True
            raise ConnectionClosed() from e
        if data[:4] == _JSON_MAGIC:
            import json as _json

            return _json.loads(data[4:])
        return pickle.loads(data)

    def close(self) -> None:
        self.closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect_unix(path: str, timeout: float = 30.0) -> MsgConnection:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout)
    sock.connect(path)
    sock.settimeout(None)
    return MsgConnection(sock)


def listen_unix(path: str) -> socket.socket:
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        import os

        os.unlink(path)
    except OSError:
        pass
    sock.bind(path)
    sock.listen(256)
    return sock


def connect_tcp(host: str, port: int, timeout: float = 30.0) -> MsgConnection:
    """TCP variant of the framed connection — the cross-host control plane
    (reference capability: gRPC services, src/ray/rpc/grpc_server.h)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return MsgConnection(sock)


def listen_tcp(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """Listening TCP socket; port 0 picks a free port (read via getsockname)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(256)
    return sock


def parse_address(address: str) -> tuple[str, str | tuple[str, int]]:
    """'unix:<path>' → ("unix", path); 'host:port' or 'tcp:host:port' →
    ("tcp", (host, port))."""
    if address.startswith("unix:"):
        return "unix", address[len("unix:"):]
    if address.startswith("tcp:"):
        address = address[len("tcp:"):]
    host, _, port = address.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


def connect_address(address: str, timeout: float = 30.0) -> MsgConnection:
    if address.startswith("proxy://"):
        # Ray-Client-style proxied connection: versioned handshake, then a
        # per-client relay bridges this socket to the GCS
        # (util/client/proxier.py)
        import socket as _socket
        import uuid as _uuid

        from ray_tpu.util.client.proxier import client_handshake

        host, _, port = address[len("proxy://"):].rpartition(":")
        sock = _socket.create_connection((host or "127.0.0.1", int(port)),
                                         timeout=timeout)
        client_handshake(sock, client_id=_uuid.uuid4().hex[:12])
        sock.settimeout(None)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        return MsgConnection(sock)
    kind, target = parse_address(address)
    if kind == "unix":
        return connect_unix(target, timeout)
    return connect_tcp(target[0], target[1], timeout)
