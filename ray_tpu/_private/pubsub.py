"""Long-poll pubsub client over the GCS control plane.

Reference capability: src/ray/pubsub/ — `Publisher`/`SubscriberState`
long-poll channels used for object locations, actor state, logs and errors
(publisher.h:159, subscriber.h:63, python_gcs_subscriber.h).

Channels published by the GCS today: ``actor_state`` (every actor
transition), ``errors`` (task failures). User code can publish to arbitrary
channels with `publish()` — fan-out is per-subscriber buffered queues with a
parked long-poll reply when the queue is empty.
"""

from __future__ import annotations

import uuid
from typing import Any, List, Optional

def _default_worker():
    """The process's CoreWorker: driver (api._worker) or task worker."""
    from ray_tpu._private import api
    from ray_tpu._private.worker import _global_worker

    w = _global_worker or api._worker
    if w is None or not hasattr(w, "rpc"):
        raise RuntimeError("pubsub requires a connected (non-local) session")
    return w


def publish(channel: str, data: Any) -> None:
    """Publish `data` to every subscriber of `channel`."""
    _default_worker().send_no_reply(
        {"type": "publish", "channel": channel, "data": data})


class Subscriber:
    """Subscribe to a GCS pubsub channel; `poll()` long-polls for batches."""

    def __init__(self, channel: str, worker=None):
        self.channel = channel
        self.sub_id = uuid.uuid4().hex[:16]
        self._worker = worker or _default_worker()
        self._closed = False
        # an outstanding long-poll future that timed out client-side: the GCS
        # still holds the parked rid and will answer it on the next publish,
        # so we must keep waiting on THIS future — issuing a fresh poll would
        # let that answer land on a dead rid and lose the batch
        self._inflight = None
        reply = self._worker.rpc({"type": "subscribe", "channel": channel,
                                  "sub_id": self.sub_id})
        if not reply.get("ok"):
            raise RuntimeError(f"subscribe failed: {reply}")

    def poll(self, timeout: Optional[float] = None) -> List[Any]:
        """Return the next batch of messages (possibly empty on timeout or
        after close)."""
        if self._closed:
            return []
        from ray_tpu.exceptions import GetTimeoutError

        if self._inflight is None:
            self._inflight = self._worker.rpc_async(
                {"type": "pubsub_poll", "channel": self.channel,
                 "sub_id": self.sub_id})
        try:
            reply = self._inflight.wait(timeout)
        except GetTimeoutError:
            return []
        self._inflight = None
        if reply.get("closed"):
            self._closed = True
        return reply.get("items", [])

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._worker.rpc({"type": "unsubscribe", "channel": self.channel,
                              "sub_id": self.sub_id}, timeout=5.0)
        except Exception:
            pass
