"""Central runtime-flag registry.

Single definition file for every tunable, typed, env-var-overridable flag,
playing the role of the reference's ``RAY_CONFIG(type, name, default)``
registry (reference: src/ray/common/ray_config_def.h, ray_config.h:60 — 229
entries materialized as a process singleton, overridable via RAY_<name> env
vars forwarded at process spawn).

Usage::

    from ray_tpu._private.ray_config import RayConfig
    if RayConfig.instance().auto_gc:
        ...

Each flag reads ``RAY_TPU_<NAME>`` (upper-cased field name) at first access;
`spawn_env()` returns the subset of flags explicitly set in this process's
environment so parent processes can forward their overrides to children the
same way the reference's `services.py` forwards `RAY_*` vars.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, fields


def _parse(typ, raw: str):
    if typ is bool:
        return raw.strip().lower() not in ("0", "false", "no", "")
    return typ(raw)


@dataclass
class RayConfig:
    # --- object store ---------------------------------------------------
    # Per-host shm store capacity in bytes before LRU spill kicks in (0 = no
    # limit). Mirrors plasma's capacity + eviction threshold.
    object_store_capacity: int = 0
    # Arena-backend capacity (cpp/shm_store.cc) in bytes (capped at 80% of
    # what /dev/shm can back at arena-creation time).
    store_capacity: int = 1 << 30
    # Store backend: "arena" (native C++ single-segment arena with LRU
    # evict-to-spill — the default: O(1) tmpfs inodes, bounded memory) or
    # "file" (one tmpfs file per object — the debuggable fallback, also
    # what init() degrades to when no C++ toolchain can build the arena).
    store_backend: str = "arena"
    # Inline-object threshold: values ≤ this many bytes live in the GCS
    # table instead of shm (reference: memory_store small-object tier).
    inline_object_limit: int = 64 * 1024
    # Chunk size for cross-host object pulls.
    object_transfer_chunk: int = 5 * 1024 * 1024
    # Object-plane server: "python" (framed MsgConnection) or "native"
    # (C++ cpp/object_server.cc — zero Python on the transfer hot path;
    # file-backed store only).
    object_server_backend: str = "python"

    # --- core worker ----------------------------------------------------
    # Distributed reference counting on ObjectRef drop (0 = manual free()).
    auto_gc: bool = True
    # Max retained task specs for lineage reconstruction (LRU).
    max_lineage: int = 10000
    # Seconds between batched refcount-delta flushes to the GCS.
    ref_flush_interval_s: float = 0.2

    # Direct dispatch: callers lease idle workers from the GCS and push
    # plain tasks to them over a dedicated connection, keeping the central
    # scheduler off the per-task hot path (reference: leased-worker
    # submission, normal_task_submitter.h:81).
    direct_dispatch: bool = True

    # --- scheduling -----------------------------------------------------
    # Utilization threshold past which the hybrid policy spreads instead of
    # packing (reference: scheduling_policy.h:66 ~50%).
    hybrid_threshold: float = 0.5
    # Default max task retries on worker death.
    default_max_retries: int = 3

    # --- cluster / transport --------------------------------------------
    # Host interface the TCP planes bind (control + object transfer).
    bind_host: str = "127.0.0.1"
    # Worker JAX platform ("cpu" keeps workers off the TPU plugin unless a
    # chip is explicitly assigned; see node.py chip isolation).
    worker_platform: str = "cpu"
    # Stream worker stdout/stderr to the driver.
    log_to_driver: bool = True
    # GCS → node-agent / worker health-check period and miss budget
    # (reference: gcs_health_check_manager.h:45, ray_config_def.h:877).
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    # Follower agents broadcast a resource-view delta (memory usage, load,
    # live worker count) this often (reference: ray_syncer RESOURCE_VIEW
    # messages); 0 disables. Feeds the GCS host table / state API /
    # dashboard.
    resource_view_interval_s: float = 2.0

    # --- collectives / fault detection ----------------------------------
    # While blocked in a host-plane collective wait, poll the liveness of
    # peer ranks' actors (via the GCS actor_info RPC) this often, so a dead
    # rank surfaces as CollectiveError within ~this interval instead of as
    # a TimeoutError after the full op timeout. 0 disables the in-wait
    # polling (a timeout still triggers one final liveness sweep).
    collective_liveness_interval_s: float = 2.0
    # How long init_collective_group waits for the rendezvous actor to
    # appear AND for all ranks to register before failing with an error
    # naming the missing ranks (previously a hardcoded 60.0).
    collective_group_create_timeout_s: float = 60.0

    # --- node drain / preemption ----------------------------------------
    # Grace window between a node being marked DRAINING and its
    # termination: how long resident train workers get to land a
    # preemption-grace checkpoint. Used by the node agent's SIGTERM
    # self-drain (the GCE preemption notice path) and as the autoscaler's
    # default drain-then-terminate window.
    drain_grace_s: float = 20.0

    # --- worker pool ----------------------------------------------------
    # Warm-pool floor: keep this many idle no-runtime-env CPU workers per
    # node, replenished asynchronously as they are consumed by dispatch or
    # leases (reference: raylet worker_pool.h:280 prestarted/cached pool —
    # first-task latency becomes a dispatch, not a process fork + imports).
    # 0 disables (init(num_workers=N) still prespawns N once; the floor
    # additionally REPLENISHES as workers are consumed).
    warm_pool_size: int = 0

    # --- memory / OOM defense -------------------------------------------
    # Host memory-monitor poll period in ms; 0 disables (reference:
    # memory_monitor.h:52 polls at memory_monitor_refresh_ms). Off by
    # default here so test runs on loaded hosts stay deterministic; node
    # deployments enable it (ray_tpu start / node_agent pass it through).
    memory_monitor_refresh_ms: int = 0
    # Usage fraction past which a victim worker is killed (reference:
    # memory_usage_threshold 0.95).
    memory_usage_threshold: float = 0.95
    # Whether the OOM killer may pick workers holding TPU chips. Off by
    # default: SIGKILLing a process mid-TPU-grant can wedge the shared
    # device pool for every other worker on the host, converting memory
    # pressure into an accelerator outage. When a chip worker IS killed
    # (opt-in), its chips are quarantined rather than returned to the
    # allocatable pool.
    oom_kill_tpu_workers: bool = False

    # --- GCS persistence ------------------------------------------------
    # Path for the GCS write-ahead table store; empty = in-memory only
    # (reference: redis_store_client.h — Redis mode = fault tolerance).
    gcs_storage_path: str = ""
    # How long a DRIVER keeps retrying to reconnect + re-register after the
    # GCS connection drops (reference: retryable_grpc_client.h). Workers
    # never reconnect — they exit and the restarted GCS respawns actors.
    gcs_reconnect_timeout_s: float = 10.0

    # --- streaming generators -------------------------------------------
    # How long a streaming producer waits at the backpressure limit with NO
    # consumer ack before failing the stream (0 = wait forever while the
    # GCS connection is alive, matching the reference's blocking behavior).
    stream_stall_timeout_s: float = 300.0

    # --- metrics / tracing ----------------------------------------------
    # Enable task timeline events (reference: ray_config_def.h:615).
    enable_timeline: bool = True
    # Max buffered task events per process before oldest are dropped.
    task_events_max: int = 10000
    # Propagate trace context (trace/span ids) inside task/actor specs
    # across process boundaries and emit spans on the task-event channel
    # (reference: python/ray/util/tracing/tracing_helper.py:165
    # _DictPropagator injecting the OTel span context into every spec).
    enable_tracing: bool = False
    # Metrics report period from workers/agents to the GCS.
    metrics_report_interval_s: float = 2.0
    # Compiled-DAG channel-plane instrumentation: per-step phase histograms
    # (input-wait / compute / output-write / backpressure-drain). The
    # always-on cost is two monotonic reads + one pre-bound histogram
    # observe per phase; 0/false disables entirely (the bench baseline).
    dag_metrics: bool = True
    # Emit a full timeline span (task_events, flushed to the GCS by the
    # CoreWorker flusher) every Nth compiled-DAG step; 0 = off. Sampled at
    # compile time into the exec-loop plan so workers need no env override.
    dag_span_sample_every: int = 100
    # Serve/PD request-path instrumentation: always-on pre-bound phase
    # histograms for the serving hot path (proxy accept/parse/route/handle,
    # handle pick/RTT, replica queue-wait/execute, PD per-page transfer
    # wait, decode-slot admission wait, inter-token gap) plus the
    # flight-recorder ring of recent request summaries. 0/false disables
    # entirely (the serving bench A/B baseline).
    serve_metrics: bool = True
    # Emit a full cross-process span tree (task_events) for every Nth serve
    # request entering the HTTP proxy; 0 = off. Same knob pattern as
    # dag_span_sample_every: sampling keeps the hot path cheap while one
    # request in N yields a complete phase timeline
    # (`ray_tpu trace show <request_id>`).
    serve_span_sample_every: int = 100
    # In-process flight recorder: how many recent request summaries each
    # serving process retains (and ships to the GCS request log) so a slow
    # request can be explained after the fact without sampling luck.
    serve_flight_recorder_size: int = 256
    # Structured cluster event log (_private/events.py): typed node/actor/
    # PG/lease lifecycle events recorded at their GCS/controller source and
    # readable via `ray_tpu events` / state.list_events(). 0/false disables
    # both emission and the GCS ring (the events bench A/B baseline).
    cluster_events: bool = True
    # Capacity of the GCS cluster-event ring (and of each producer-side
    # buffer); oldest events fall off. Persisted INFO+ events in the sqlite
    # `events` table are bounded to the same count.
    cluster_events_ring_size: int = 4096
    # --- serve proxy plane ----------------------------------------------
    # Number of proxy shard processes serve.start() launches when the
    # sharded plane is requested without an explicit num_proxies. 0 keeps
    # the legacy single in-driver ProxyActor (the default: tests and small
    # deployments need no extra worker processes).
    serve_num_proxies: int = 0
    # Ceiling on buffered HTTP request bodies: a Content-Length above this
    # is refused with 413 before any body bytes are read, and a chunked/
    # unframed body is cut off at the cap. Headers are bounded separately
    # (http_server.MAX_HEADER_BYTES).
    serve_max_http_body_bytes: int = 64 * 1024 * 1024
    # Zero-copy payload threshold: HTTP bodies / replica results at or
    # above this many bytes move proxy<->replica through the arena object
    # plane (envelope carries the object id, never a pickled body through
    # fast-RPC or the GCS). Must exceed inline_object_limit or the "zero
    # copy" path would just move the bytes into the GCS table instead.
    serve_zero_copy_threshold_bytes: int = 256 * 1024
    # Serve telemetry batching: when > 0, proxy-shard phase observes are
    # buffered locally and flushed into the real histograms once per this
    # interval (one lock acquisition per flush instead of per request).
    # 0 = observe synchronously per request (the legacy single proxy).
    serve_telemetry_flush_s: float = 0.5
    # Capacity of the seqlock shm segment the controller publishes the
    # routing table into. A table that serializes past this falls back to
    # controller-RPC refresh (proxies log once and keep serving).
    serve_routing_shm_bytes: int = 1 << 20
    # HTTP proxy per-request budget: ceiling on the blocking handle call
    # behind each non-streaming HTTP request (previously a hardcoded 60 s).
    # A request carrying its own deadline (x-ray-tpu-deadline-s header)
    # clamps further to the remaining budget; expiry surfaces as 504.
    serve_request_timeout_s: float = 60.0
    # Compiled-DAG exec-loop recovery budget: total seconds the driver
    # waits per recovery for the core actor restart + the in-band rewire
    # barrier + the in-flight replay before degrading the DAG to the
    # submit-path fallback.
    dag_recovery_timeout_s: float = 60.0

    # --- data plane fault tolerance -------------------------------------
    # Master switch for Data-plane fault handling (per-block retry, pool
    # actor replacement, lineage-backed barrier recovery). Off = legacy
    # fail-fast behavior (the DATA_BENCH A/B baseline).
    data_fault_tolerance: bool = True
    # Max resubmissions per block after a SYSTEM error (actor death /
    # worker crash / lost object). Exhausting the budget raises
    # DataBlockError(kind="system") naming the block.
    data_max_block_retries: int = 3
    # Base for the full-jitter retry backoff: sleep ~uniform(0,
    # base * 2**attempt), capped at 8x base (PR 2 idiom, injectable rng).
    data_retry_backoff_s: float = 0.25
    # How many dead `_MapPoolActor`s a pool may replace over its lifetime
    # (-1 = unlimited). Exhausting it with zero survivors fails the
    # pipeline rather than hanging it.
    data_actor_restart_budget: int = 4
    # Transient-IO retries per file inside datasource read tasks (OSError
    # except FileNotFoundError), and their backoff base. Failures carry
    # per-file attribution.
    data_read_retries: int = 2
    data_read_retry_backoff_s: float = 0.2
    # APPLICATION-error (UDF raise) policy: "raise" surfaces the first
    # errored block; "skip" drops it (counted + logged with block id)
    # until max_errored_blocks is exceeded (-1 = unlimited skips).
    # Retried SYSTEM errors never consume this budget.
    data_on_block_error: str = "raise"
    data_max_errored_blocks: int = -1

    _singleton = None
    _lock = threading.Lock()

    @classmethod
    def instance(cls) -> "RayConfig":
        if cls._singleton is None:
            with cls._lock:
                if cls._singleton is None:
                    cls._singleton = cls._from_env()
        return cls._singleton

    @classmethod
    def _from_env(cls) -> "RayConfig":
        cfg = cls()
        for f in fields(cls):
            if f.name.startswith("_"):
                continue
            raw = os.environ.get("RAY_TPU_" + f.name.upper())
            if raw is not None:
                try:
                    setattr(cfg, f.name, _parse(f.type if isinstance(f.type, type)
                                                else type(f.default), raw))
                except (TypeError, ValueError):
                    pass  # malformed override: keep the default
        return cfg

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (tests set env vars then re-read)."""
        with cls._lock:
            cls._singleton = None

    @classmethod
    def get(cls, name: str):
        """Fresh typed read of one flag (env consulted every call — for
        construction-time reads where tests change env between sessions
        within one process; use instance() on hot paths)."""
        for f in fields(cls):
            if f.name == name:
                raw = os.environ.get("RAY_TPU_" + name.upper())
                if raw is None:
                    return f.default
                try:
                    return _parse(f.type if isinstance(f.type, type)
                                  else type(f.default), raw)
                except (TypeError, ValueError):
                    return f.default
        raise AttributeError(f"unknown ray config flag {name!r}")

    @staticmethod
    def spawn_env() -> dict:
        """Flags explicitly set in this process's env, for child processes."""
        out = {}
        for f in fields(RayConfig):
            if f.name.startswith("_"):
                continue
            key = "RAY_TPU_" + f.name.upper()
            if key in os.environ:
                out[key] = os.environ[key]
        return out
