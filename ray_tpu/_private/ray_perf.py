"""Core runtime microbenchmarks — `python -m ray_tpu._private.ray_perf`.

Measures the same op classes as the reference's `ray microbenchmark`
(reference: python/ray/_private/ray_perf.py:95-330 — put/get latency, task
throughput sync/async, 1:1/1:n actor calls) and writes MICROBENCH.json at the
repo root so numbers are committed and compared round-over-round
(VERDICT.md round-1 item 7).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def timeit(name, fn, multiplier: int = 1, min_seconds: float = 2.0) -> dict:
    """Run fn repeatedly for >= min_seconds, report ops/s (fn = 1*multiplier ops)."""
    fn()  # warmup
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_seconds:
        fn()
        count += 1
    dt = time.perf_counter() - start
    ops = count * multiplier / dt
    rec = {"name": name, "ops_per_s": round(ops, 1),
           "us_per_op": round(1e6 / ops, 1)}
    print(f"{name:48s} {ops:12.1f} ops/s   {1e6 / ops:10.1f} us/op")
    return rec


def main():
    import ray_tpu

    ray_tpu.shutdown()
    # 8 logical CPUs regardless of host cores: the suite holds 5 actors
    # live at once (1 + a 4-actor scatter group) plus task workers — a
    # 4-CPU session would park the 5th creation forever
    ray_tpu.init(num_cpus=max(8, os.cpu_count() or 8), num_workers=4,
                 max_workers=10)
    results = []

    try:
        # ---- object plane -------------------------------------------------
        small = b"x" * 1024
        results.append(timeit(
            "put_small_1KiB", lambda: ray_tpu.put(small)))

        arr = np.zeros(1 << 18, dtype=np.float64)  # 2 MiB → shm path
        results.append(timeit(
            "put_numpy_2MiB", lambda: ray_tpu.put(arr)))

        ref_small = ray_tpu.put(small)
        results.append(timeit(
            "get_small_1KiB", lambda: ray_tpu.get(ref_small)))

        ref_big = ray_tpu.put(arr)
        results.append(timeit(
            "get_numpy_2MiB_zero_copy", lambda: ray_tpu.get(ref_big)))

        # honest store-path get: every object fetched exactly once (the
        # _zero_copy number above re-reads one cached mmap — real, but not
        # comparable to the reference's fresh-object methodology)
        fresh = np.zeros(1 << 15, dtype=np.float64)  # 256 KiB → shm path
        pool = [ray_tpu.put(fresh) for _ in range(400)]
        it = iter(pool)
        t0 = time.perf_counter()
        n_got = 0
        for ref in it:
            ray_tpu.get(ref)
            n_got += 1
            if time.perf_counter() - t0 > 2.0:
                break
        dt = (time.perf_counter() - t0) / max(n_got, 1)
        rec = {"name": "get_numpy_256KiB_fresh",
               "ops_per_s": round(1 / dt, 1), "us_per_op": round(dt * 1e6, 1)}
        print(f"{'get_numpy_256KiB_fresh':48s} {1 / dt:12.1f} ops/s   "
              f"{dt * 1e6:10.1f} us/op")
        results.append(rec)
        del pool  # auto-GC frees the shm copies

        # ---- tasks --------------------------------------------------------
        @ray_tpu.remote
        def nop():
            return b"ok"

        results.append(timeit(
            "task_sync_roundtrip", lambda: ray_tpu.get(nop.remote())))

        def batch_tasks():
            ray_tpu.get([nop.remote() for _ in range(100)])

        results.append(timeit(
            "task_async_batch100", batch_tasks, multiplier=100))

        # ---- actors -------------------------------------------------------
        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        a = Counter.remote()
        ray_tpu.get(a.inc.remote())
        results.append(timeit(
            "actor_call_sync_1to1", lambda: ray_tpu.get(a.inc.remote())))

        def actor_batch():
            ray_tpu.get([a.inc.remote() for _ in range(100)])

        results.append(timeit(
            "actor_call_async_batch100_1to1", actor_batch, multiplier=100))

        actors = [Counter.remote() for _ in range(4)]
        ray_tpu.get([x.inc.remote() for x in actors])

        def scatter():
            ray_tpu.get([x.inc.remote() for x in actors for _ in range(25)])

        results.append(timeit(
            "actor_call_async_batch100_1toN", scatter, multiplier=100))
    finally:
        ray_tpu.shutdown()

    path = os.path.abspath(os.path.join(
        os.path.dirname(__file__), "..", "..", "MICROBENCH.json"))
    merge_microbench(path, results)
    print(f"\nwrote {path}")


if __name__ == "__main__":
    sys.exit(main())


def merge_microbench(path: str, results: list) -> None:
    """Write benchmark rows into MICROBENCH.json, preserving rows owned by
    OTHER benchmarks (core microbench, scheduler scale, warm pool,
    control-plane ceilings all share the artifact — a rerun of one must
    not wipe the rest)."""
    mine = {r["name"] for r in results}
    prior = []
    try:
        with open(path) as f:
            prior = [r for r in json.load(f).get("results", [])
                     if r.get("name") not in mine]
    except (OSError, ValueError):
        pass
    out = {
        "recorded_at_round": os.environ.get("RAY_TPU_BENCH_ROUND", ""),
        "results": results + prior,
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=1)
    os.replace(tmp, path)
