"""Per-host runtime-env agent: a dedicated process that builds runtime
environments (pip venvs, conda prefixes) on request.

(reference: python/ray/_private/runtime_env/agent/ — the raylet delegates
GetOrCreateRuntimeEnv to a per-node agent process so env creation is
deduplicated, asynchronous to scheduling, observable, and a broken env
fails fast instead of boot-looping workers.)

Here the spawners keep launching workers immediately (scheduling never
waits on pip); the worker BOOT shim asks this agent to get-or-create its
env instead of building it in-process. Concurrent workers needing the
same env share ONE build (an in-flight table, not just the file lock),
the agent caches results, and `list` exposes build status/errors to the
state API. If the agent is unreachable the shim falls back to the local
build path, so the agent is an optimization + observability layer, never
a single point of failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import traceback

from ray_tpu._private.protocol import (ConnectionClosed, MsgConnection,
                                       connect_unix, listen_unix)

ENV_VAR = "RAY_TPU_RENV_AGENT_SOCK"


def _build(renv: dict) -> dict:
    """Build whatever the env needs; returns {"python": interpreter}."""
    python = sys.executable
    conda_spec = renv.get("conda")
    pip_spec = renv.get("pip")
    if conda_spec and pip_spec:
        # same restriction as the reference: pip packages belong INSIDE the
        # conda spec's dependencies; two interpreters cannot both win
        raise ValueError(
            "runtime_env cannot combine 'conda' and 'pip' — put pip "
            "packages under the conda spec's dependencies instead")
    if conda_spec:
        from ray_tpu._private.runtime_env_conda import ensure_conda_env

        python = ensure_conda_env(conda_spec)
    if pip_spec:
        from ray_tpu._private.runtime_env_pip import ensure_venv

        python = ensure_venv(pip_spec)
    return {"python": python}


def _env_key(renv: dict) -> str:
    return json.dumps({k: renv.get(k) for k in ("pip", "conda")},
                      sort_keys=True)


class RuntimeEnvAgent:
    """Framed-protocol server over a unix socket; one per host."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._lock = threading.Lock()
        # key → {"state": building|ready|failed, "event", "result", "error",
        #         "refs": int}
        self._envs: dict[str, dict] = {}
        self._listener = listen_unix(socket_path)
        self._stop = False

    # ------------------------------------------------------------- server

    def serve_forever(self):
        while not self._stop:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn,
                             args=(MsgConnection(sock),),
                             daemon=True).start()

    def _serve_conn(self, conn: MsgConnection):
        try:
            while True:
                msg = conn.recv()
                try:
                    reply = self._dispatch(msg)
                except Exception as e:  # noqa: BLE001 — agent must survive
                    reply = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"}
                reply["rid"] = msg.get("rid")
                conn.send(reply)
        except ConnectionClosed:
            pass

    def _dispatch(self, msg: dict) -> dict:
        t = msg.get("t")
        if t == "ping":
            return {"ok": True, "pid": os.getpid()}
        if t == "get_or_create":
            return self._get_or_create(msg.get("renv") or {})
        if t == "list":
            with self._lock:
                return {"ok": True, "envs": {
                    k: {"state": e["state"], "refs": e["refs"],
                        "error": e.get("error")}
                    for k, e in self._envs.items()}}
        if t == "shutdown":
            self._stop = True
            threading.Thread(target=self.stop, daemon=True).start()
            return {"ok": True}
        return {"ok": False, "error": f"unknown message {t!r}"}

    # -------------------------------------------------------------- logic

    def _get_or_create(self, renv: dict) -> dict:
        key = _env_key(renv)
        with self._lock:
            ent = self._envs.get(key)
            if ent is not None and ent["state"] == "failed":
                # failures don't poison the key: waiters of the original
                # build saw the error; each NEW request retries (transient
                # pip/network failures heal, like the old per-worker path)
                self._envs.pop(key)
                ent = None
            if ent is None:
                ent = {"state": "building", "event": threading.Event(),
                       "result": None, "error": None, "refs": 0}
                self._envs[key] = ent
                builder = threading.Thread(
                    target=self._run_build, args=(key, renv), daemon=True)
                builder.start()
            ent["refs"] += 1
        ent["event"].wait()
        if ent["state"] == "ready":
            return {"ok": True, **ent["result"]}
        return {"ok": False, "error": ent["error"]}

    def _run_build(self, key: str, renv: dict):
        ent = self._envs[key]
        try:
            ent["result"] = _build(renv)
            ent["state"] = "ready"
        except Exception as e:  # noqa: BLE001 — report, don't die
            ent["error"] = "".join(traceback.format_exception_only(e)).strip()
            ent["state"] = "failed"
        finally:
            ent["event"].set()

    def stop(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


class AgentHandle:
    """Lazily-started agent SUBPROCESS owned by a spawner (head node or
    follower node-agent). ensure() starts it on first use and returns the
    socket path to bake into worker envs."""

    def __init__(self, session_dir: str):
        self.socket_path = os.path.join(session_dir, "renv_agent.sock")
        self._log_path = os.path.join(session_dir, "logs",
                                      "runtime_env_agent.log")
        self.proc = None
        self._lock = threading.Lock()

    def ensure(self) -> str:
        import subprocess
        import time

        with self._lock:
            if self.proc is not None and self.proc.poll() is None:
                return self.socket_path
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)  # agent never touches TPU
            env["JAX_PLATFORMS"] = "cpu"
            log = open(self._log_path, "ab")
            try:
                self.proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "ray_tpu._private.runtime_env_agent",
                     "--socket", self.socket_path],
                    env=env, stdout=log, stderr=subprocess.STDOUT)
            finally:
                log.close()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if os.path.exists(self.socket_path):
                    try:
                        conn = connect_unix(self.socket_path, timeout=2.0)
                        conn.send({"t": "ping", "rid": 0})
                        conn.recv()
                        conn.close()
                        return self.socket_path
                    except (OSError, ConnectionClosed):
                        pass
                time.sleep(0.05)
            # reset: a half-started process left in self.proc would make
            # every later ensure() return an unconnectable socket path
            proc, self.proc = self.proc, None
            try:
                proc.kill()
            except OSError:
                pass
            raise RuntimeError("runtime-env agent failed to come up "
                               f"(see {self._log_path})")

    def stop(self):
        with self._lock:
            if self.proc is not None and self.proc.poll() is None:
                self.proc.terminate()
                try:
                    self.proc.wait(timeout=3)
                except Exception:
                    self.proc.kill()
            self.proc = None


# ------------------------------------------------------------------ client


def get_or_create(socket_path: str, renv: dict,
                  timeout: float = 600.0) -> dict:
    """Client call used by worker_boot; raises on agent-reported failure."""
    conn = connect_unix(socket_path, timeout=5.0)
    try:
        conn.send({"t": "get_or_create", "renv": renv, "rid": 1})
        conn.sock.settimeout(timeout)
        reply = conn.recv()
        if not reply.get("ok"):
            raise RuntimeError(
                f"runtime env creation failed: {reply.get('error')}")
        return reply
    finally:
        conn.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu runtime-env-agent")
    ap.add_argument("--socket", required=True)
    args = ap.parse_args(argv)
    agent = RuntimeEnvAgent(args.socket)
    try:
        agent.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        agent.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
