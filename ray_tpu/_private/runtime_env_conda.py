"""conda runtime environments: named envs or spec-created envs per worker.

runtime_env={"conda": "existing-env-name"} runs the worker under that
conda env's interpreter; {"conda": {"dependencies": [...]}} creates (and
caches, keyed by spec hash) a prefix env under the session base.

(reference: python/ray/_private/runtime_env/conda.py — get_conda_activate
commands + per-job env creation keyed by a hash of the spec. Same model:
creation happens in the WORKER process (worker_boot), never the scheduler
thread; the conda binary is discovered from $CONDA_EXE/PATH and its
absence is a clear user error, not a crash.)
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess


def conda_base() -> str:
    from ray_tpu._private.runtime_env_pip import secure_user_base

    return secure_user_base("RAY_TPU_CONDA_ENV_BASE", "ray_tpu_conda")


def find_conda(conda_exe: str | None = None) -> str:
    exe = (conda_exe or os.environ.get("CONDA_EXE")
           or shutil.which("conda") or shutil.which("mamba")
           or shutil.which("micromamba"))
    if not exe:
        raise RuntimeError(
            "runtime_env['conda'] requires a conda/mamba binary on the "
            "worker host (none on PATH and $CONDA_EXE unset)")
    return exe


def normalize_conda(spec) -> str | dict:
    """Named env → str; inline spec → canonical
    {dependencies: [...], channels?: [...]}. Unknown keys are rejected —
    silently dropping e.g. channels would build a DIFFERENT env than the
    user asked for and collide cache hashes across channel lists."""
    if isinstance(spec, str):
        return spec
    if isinstance(spec, dict):
        extra = set(spec) - {"dependencies", "channels", "name"}
        if extra:
            raise TypeError(
                f"unsupported conda spec keys {sorted(extra)} (supported: "
                "dependencies, channels, name)")
        deps = spec.get("dependencies")
        if not isinstance(deps, list) or not deps:
            raise TypeError(
                "runtime_env['conda'] dict needs a non-empty "
                "'dependencies' list (conda environment.yml schema)")
        out = {"dependencies": _canon_deps(deps)}
        channels = spec.get("channels")
        if channels:
            if not all(isinstance(c, str) for c in channels):
                raise TypeError("conda 'channels' must be strings")
            out["channels"] = list(channels)  # ORDER is priority: keep it
        return out
    raise TypeError("runtime_env['conda'] must be an env name (str) or an "
                    "environment.yml-style dict")


def _canon_deps(deps: list):
    out = []
    for d in deps:
        if isinstance(d, str):
            out.append(d)
        elif isinstance(d, dict) and list(d) == ["pip"]:
            out.append({"pip": sorted(str(x) for x in d["pip"])})
        else:
            raise TypeError(f"unsupported conda dependency entry {d!r}")
    # plain entries sort; a pip sub-dict stays last (conda requirement)
    plain = sorted(x for x in out if isinstance(x, str))
    pips = [x for x in out if isinstance(x, dict)]
    return plain + pips


def conda_hash(normalized) -> str:
    return hashlib.sha1(
        json.dumps(normalized, sort_keys=True).encode()).hexdigest()[:16]


def _env_yaml(normalized: dict) -> str:
    """environment.yml text from the canonical spec (hand-rendered: the
    schema subset here is flat lists, no yaml dependency needed)."""
    lines = []
    if normalized.get("channels"):
        lines.append("channels:")
        lines.extend(f"  - {c}" for c in normalized["channels"])
    lines.append("dependencies:")
    for d in normalized["dependencies"]:
        if isinstance(d, str):
            lines.append(f"  - {d}")
        else:
            lines.append("  - pip:")
            for p in d["pip"]:
                lines.append(f"      - {p}")
    return "\n".join(lines) + "\n"


def _prefix_python(prefix: str) -> str:
    return os.path.join(prefix, "bin", "python")


def ensure_conda_env(spec, *, conda_exe: str | None = None,
                     runner=subprocess.run) -> str:
    """Return the interpreter path for this conda runtime env, creating a
    prefix env on first use for inline specs. `runner` is injectable so
    the command construction is testable without a conda install."""
    normalized = normalize_conda(spec)
    exe = find_conda(conda_exe)
    if isinstance(normalized, str):
        # named env: ask conda where it lives (works for -n registered envs)
        r = runner([exe, "run", "-n", normalized, "python", "-c",
                    "import sys; print(sys.executable)"],
                   capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            raise RuntimeError(
                f"conda env {normalized!r} not usable:\n{r.stderr[-1000:]}")
        return r.stdout.strip().splitlines()[-1]
    import fcntl

    h = conda_hash(normalized)
    base = conda_base()
    prefix = os.path.join(base, h)
    python = _prefix_python(prefix)
    marker = prefix + ".ready"
    if os.path.exists(marker):
        return python
    # flock so concurrent workers with the same spec build the env once
    # (mirrors runtime_env_pip.ensure_venv)
    with open(os.path.join(base, f"{h}.lock"), "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                return python
            yml = os.path.join(base, f"{h}.yml")
            with open(yml, "w") as f:
                f.write(_env_yaml(normalized))
            r = runner([exe, "env", "create", "--yes", "-p", prefix,
                        "-f", yml],
                       capture_output=True, text=True, timeout=1200)
            if r.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed:\n{r.stderr[-2000:]}")
            with open(marker, "w") as f:
                f.write("ok")
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
    return python
