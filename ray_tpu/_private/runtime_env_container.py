"""Container runtime environments: workers run inside an image.

runtime_env={"image_uri": "docker.io/org/img:tag"} wraps the worker
process in `podman run` (or docker — discovered from PATH, override via
RAY_TPU_CONTAINER_ENGINE), mounting the session directory (sockets, logs,
shm object files) and the ray_tpu source so the containerized worker joins
the same cluster.

(reference: python/ray/_private/runtime_env/image_uri.py — worker
processes run under `podman run` with the session dir mounted; same
contract here, argv construction kept pure so it's testable without a
container engine.)
"""

from __future__ import annotations

import os
import shutil


def find_engine(engine: str | None = None) -> str:
    exe = (engine or os.environ.get("RAY_TPU_CONTAINER_ENGINE")
           or shutil.which("podman") or shutil.which("docker"))
    if not exe:
        raise RuntimeError(
            "runtime_env['image_uri'] requires a container engine "
            "(podman or docker) on the worker host — none found on PATH "
            "and $RAY_TPU_CONTAINER_ENGINE unset")
    return exe


def normalize_image_uri(uri) -> str:
    if not isinstance(uri, str) or not uri.strip():
        raise TypeError("runtime_env['image_uri'] must be a non-empty "
                        "image reference string")
    return uri.strip()


def container_argv(image_uri: str, worker_argv: list, env: dict, *,
                   session_dir: str, engine: str,
                   extra_mounts: tuple = ()) -> list:
    """The full `engine run ...` argv for one worker process. Pure
    function of its inputs (reference behavior: image_uri.py builds a
    podman command with --env/-v and host networking)."""
    argv = [engine, "run", "--rm", "--network=host", "--ipc=host",
            "--pid=host"]
    # the session dir carries the GCS socket, logs, and /dev/shm-backed
    # object files the worker must share with the host cluster
    mounts = [session_dir, "/dev/shm", _repo_root(), *extra_mounts]
    for m in mounts:
        argv += ["-v", f"{m}:{m}"]
    for k in sorted(env):
        argv += ["--env", f"{k}={env[k]}"]
    pkg_parent = _repo_root()
    pp_parts = [pkg_parent] + [p for p in
                               env.get("PYTHONPATH", "").split(os.pathsep)
                               if p]  # no empty entries: "" = cwd on sys.path
    argv += ["--env", "PYTHONPATH=" + os.pathsep.join(pp_parts)]
    argv += ["--workdir", session_dir]
    argv.append(image_uri)
    worker_argv = list(worker_argv)
    # the HOST interpreter path doesn't exist inside the image: the image
    # provides the python (with the framework's deps); PATH resolves it
    if worker_argv and os.path.isabs(worker_argv[0]) \
            and os.path.basename(worker_argv[0]).startswith("python"):
        worker_argv[0] = "python3"
    argv += worker_argv
    return argv


def build_worker_argv(runtime_env: dict | None, env: dict,
                      session_dir: str, entry: str) -> list:
    """The spawn argv for one worker given its runtime env — shared by the
    head-node and follower-agent spawners so entry selection and container
    wrapping stay in ONE place."""
    import sys

    argv = [sys.executable, "-m", entry]
    if runtime_env and runtime_env.get("image_uri"):
        argv = container_argv(runtime_env["image_uri"], argv, env,
                              session_dir=session_dir, engine=find_engine())
    return argv


def boot_entry(runtime_env: dict | None) -> str:
    """worker_boot (env built in the worker) vs worker_main (direct)."""
    if runtime_env and (runtime_env.get("pip") or runtime_env.get("conda")):
        return "ray_tpu._private.worker_boot"
    return "ray_tpu._private.worker_main"


def _repo_root() -> str:
    """Directory containing the ray_tpu package (mounted so the container
    runs the same framework code as the host)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
