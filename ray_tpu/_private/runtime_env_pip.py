"""pip runtime environments: isolated venvs keyed by requirements hash.

A task/actor with runtime_env={"pip": [...]} runs in a worker whose
interpreter is a virtualenv built from those requirements. Venvs are
content-addressed by the requirement list and cached per host; creation is
flock-guarded so concurrent workers build once. Venvs inherit the host's
site-packages (--system-site-packages) so the baked-in jax/numpy stack
stays available and only the delta installs.

Requirement entries are requirements.txt lines, so pip global options
("--no-index", "--no-build-isolation", local paths) work — which is also
how hermetic/offline installs are expressed.

(reference: python/ray/_private/runtime_env/pip.py — per-node pip env
creation with caching and locking, delegated to the runtime-env agent;
here the worker-boot shim builds the env in the worker process itself so
the control plane never blocks on pip.)
"""

from __future__ import annotations

import fcntl
import hashlib
import json
import os
import subprocess
import sys

PIP_TIMEOUT_S = 600.0


def secure_user_base(env_var: str, prefix: str) -> str:
    """Per-user 0700 cache directory (override via `env_var`). A fixed
    world-writable path would let another local user pre-plant an env at a
    predictable content hash that worker_boot would exec — shared hardening
    for every env cache (pip venvs, conda prefixes)."""
    import stat
    import tempfile

    base = os.environ.get(env_var) or os.path.join(
        tempfile.gettempdir(), f"{prefix}_{os.getuid()}")
    os.makedirs(base, mode=0o700, exist_ok=True)
    info = os.stat(base)
    if info.st_uid != os.getuid() or info.st_mode & (stat.S_IWGRP | stat.S_IWOTH):
        raise RuntimeError(
            f"refusing env base {base!r}: not owned by uid {os.getuid()} "
            "or group/world-writable")
    return base


def venv_base() -> str:
    return secure_user_base("RAY_TPU_VENV_BASE", "ray_tpu_venvs")


def pip_hash(entries: list[str]) -> str:
    return hashlib.sha1(json.dumps(list(entries)).encode()).hexdigest()[:16]


def normalize_pip(spec) -> list[str]:
    """Accept list[str] or {"packages": [...]} (reference schema)."""
    if isinstance(spec, dict):
        spec = spec.get("packages") or []
    if isinstance(spec, str):
        spec = [spec]
    if not isinstance(spec, (list, tuple)) or not all(
            isinstance(x, str) for x in spec):
        raise TypeError("runtime_env['pip'] must be a list of requirement "
                        "strings or {'packages': [...]}")
    return list(spec)


def ensure_venv(entries: list[str]) -> str:
    """Create (or reuse) the venv for `entries`; returns its python path."""
    h = pip_hash(entries)
    base = venv_base()
    dest = os.path.join(base, h)
    python = os.path.join(dest, "bin", "python")
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return python
    lock_path = os.path.join(base, f".{h}.lock")
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):  # another worker built it meanwhile
                return python
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 dest],
                check=True, capture_output=True, timeout=PIP_TIMEOUT_S)
            # --system-site-packages exposes the BASE python's site dir; when
            # this interpreter is itself a venv (the usual deployment), the
            # host stack (setuptools/jax/numpy/...) lives in THIS venv's
            # site-packages — bridge it with a .pth so the child env sees it
            # (venv-local installs still shadow it: .pth paths come later)
            import site

            parents = [p for p in site.getsitepackages() if os.path.isdir(p)]
            vsite = subprocess.run(
                [os.path.join(dest, "bin", "python"), "-c",
                 "import site; print(site.getsitepackages()[-1])"],
                capture_output=True, text=True,
                timeout=60).stdout.strip()
            if vsite and parents:
                with open(os.path.join(vsite, "_ray_tpu_parent.pth"), "w") as f:
                    f.write("\n".join(parents) + "\n")
            # "--"-prefixed entries are pip CLI flags ("--no-index",
            # "--no-build-isolation", ...); the rest are requirement lines
            cli = [e for e in entries if e.startswith("--")]
            lines = [e for e in entries if not e.startswith("--")]
            reqs = os.path.join(dest, "requirements.txt")
            with open(reqs, "w") as f:
                f.write("\n".join(lines) + "\n")
            r = subprocess.run(
                [python, "-m", "pip", "install",
                 "--disable-pip-version-check", "--no-input", *cli,
                 "-r", reqs],
                capture_output=True, text=True, timeout=PIP_TIMEOUT_S)
            if r.returncode != 0:
                raise RuntimeError(
                    f"pip install for runtime_env failed:\n{r.stderr[-2000:]}")
            with open(marker, "w"):
                pass
            return python
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def interpreter_for(normalized_env: dict | None) -> str:
    """The python executable a worker with this runtime env must run under."""
    if normalized_env and normalized_env.get("pip"):
        return ensure_venv(normalized_env["pip"])
    return sys.executable
