"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Layout of a serialized payload:

    [8-byte little-endian pickle length][pickle bytes]
    [8-byte n_buffers][for each buffer: 8-byte length][buffer bytes]

Out-of-band buffers let numpy arrays round-trip zero-copy when the payload is
mmap'd from the shared-memory object store (reference:
python/ray/_private/serialization.py + arrow_serialization.py do the same via
pickle protocol 5).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import cloudpickle

_U64 = struct.Struct("<Q")


def load_class_by_ref(module: str, qualname: str, search_path: str | None = None):
    """Import `module` and return the class named `qualname`, unwrapping an
    @remote ActorClass wrapper if the module attribute is one. `search_path`
    (the defining file's directory on the driver) is appended to sys.path as
    a fallback — workers may lack the driver script's sys.path[0]."""
    import importlib
    import sys

    from ray_tpu.actor import ActorClass

    try:
        mod = importlib.import_module(module)
    except ModuleNotFoundError:
        if not search_path or search_path in sys.path:
            raise
        sys.path.append(search_path)
        mod = importlib.import_module(module)
    obj = getattr(mod, qualname)
    return obj.cls if isinstance(obj, ActorClass) else obj


class ClassByRef:
    """Pickles as an import reference; loads() yields the class itself.

    Used for actor classes that are importable on workers: @remote rebinds
    the module attribute to the ActorClass wrapper, which defeats
    cloudpickle's by-reference logic and forces by-value class pickling
    (fragile — class bodies referencing unpicklable module globals fail, and
    blobs are large). (reference: the function/actor export path registers
    importable code by reference too, _private/function_manager.py.)"""

    def __init__(self, module: str, qualname: str, search_path: str | None = None):
        self.module = module
        self.qualname = qualname
        self.search_path = search_path

    def __reduce__(self):
        return (load_class_by_ref, (self.module, self.qualname, self.search_path))


def class_ref_or_none(cls) -> "ClassByRef | None":
    """Return a ClassByRef if `cls` is reachable by import, else None."""
    import sys

    module = getattr(cls, "__module__", None)
    qualname = getattr(cls, "__qualname__", "")
    if not module or module == "__main__" or "." in qualname or "<locals>" in qualname:
        return None
    mod = sys.modules.get(module)
    if mod is None:
        return None
    try:
        if load_class_by_ref(module, qualname) is cls:
            import os

            src = getattr(mod, "__file__", None)
            return ClassByRef(module, qualname,
                              os.path.dirname(src) if src else None)
    except Exception:
        return None
    return None


def dumps(obj: Any) -> bytes:
    buffers: list[pickle.PickleBuffer] = []
    pick = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts = [_U64.pack(len(pick)), pick, _U64.pack(len(buffers))]
    for b in buffers:
        raw = b.raw()
        parts.append(_U64.pack(raw.nbytes))
        parts.append(raw)
    return b"".join(parts)


def dumps_into(obj: Any) -> tuple[list[bytes | memoryview], int]:
    """Like dumps but returns (parts, total_size) without joining — lets the
    object store write directly into shm without an extra copy."""
    buffers: list[pickle.PickleBuffer] = []
    pick = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts: list[bytes | memoryview] = [_U64.pack(len(pick)), pick, _U64.pack(len(buffers))]
    total = 8 + len(pick) + 8
    for b in buffers:
        raw = b.raw()
        parts.append(_U64.pack(raw.nbytes))
        parts.append(raw)
        total += 8 + raw.nbytes
    return parts, total


def _tethered(view: memoryview, owner: Any):
    """Wrap a zero-copy buffer slice so its consumers keep `owner` alive.

    Out-of-band buffers become the base of the numpy arrays pickle
    reconstructs; a plain memoryview keeps only the mmap alive, NOT the
    store pin wrapper — so freeing the ref would release the pin and let
    the arena recycle the slot under live views (plasma semantics: a Get
    buffer pins the entry, and deleting a pinned entry defers space reuse
    until the final release — cpp/shm_store.cc kDeleting). A ctypes array
    is the one pure-Python buffer exporter that reports ITSELF as the
    owner of derived memoryviews (numpy's export redirects to the root
    base, so an ndarray-subclass tether gets collapsed away)."""
    import ctypes

    try:
        t = (ctypes.c_char * view.nbytes).from_buffer(view)
    except (TypeError, ValueError):
        # read-only buffer: the file-backend PROT_READ mmap. Unlinked-file
        # pages persist while mapped, so there is no reuse hazard to pin
        # against — the plain view is safe there.
        return view
    t._tether_owner = owner
    return t


def loads(data: bytes | memoryview, owner: Any = None) -> Any:
    view = memoryview(data)
    (pick_len,) = _U64.unpack_from(view, 0)
    pick = view[8 : 8 + pick_len]
    off = 8 + pick_len
    (n_buf,) = _U64.unpack_from(view, off)
    off += 8
    buffers = []
    for _ in range(n_buf):
        (blen,) = _U64.unpack_from(view, off)
        off += 8
        b = view[off : off + blen]
        buffers.append(_tethered(b, owner) if owner is not None else b)
        off += blen
    return pickle.loads(pick, buffers=buffers)
