"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Layout of a serialized payload:

    [8-byte little-endian pickle length][pickle bytes]
    [8-byte n_buffers][for each buffer: 8-byte length][buffer bytes]

Out-of-band buffers let numpy arrays round-trip zero-copy when the payload is
mmap'd from the shared-memory object store (reference:
python/ray/_private/serialization.py + arrow_serialization.py do the same via
pickle protocol 5).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any

import cloudpickle

_U64 = struct.Struct("<Q")


def dumps(obj: Any) -> bytes:
    buffers: list[pickle.PickleBuffer] = []
    pick = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts = [_U64.pack(len(pick)), pick, _U64.pack(len(buffers))]
    for b in buffers:
        raw = b.raw()
        parts.append(_U64.pack(raw.nbytes))
        parts.append(raw)
    return b"".join(parts)


def dumps_into(obj: Any) -> tuple[list[bytes | memoryview], int]:
    """Like dumps but returns (parts, total_size) without joining — lets the
    object store write directly into shm without an extra copy."""
    buffers: list[pickle.PickleBuffer] = []
    pick = cloudpickle.dumps(obj, protocol=5, buffer_callback=buffers.append)
    parts: list[bytes | memoryview] = [_U64.pack(len(pick)), pick, _U64.pack(len(buffers))]
    total = 8 + len(pick) + 8
    for b in buffers:
        raw = b.raw()
        parts.append(_U64.pack(raw.nbytes))
        parts.append(raw)
        total += 8 + raw.nbytes
    return parts, total


def loads(data: bytes | memoryview) -> Any:
    view = memoryview(data)
    (pick_len,) = _U64.unpack_from(view, 0)
    pick = view[8 : 8 + pick_len]
    off = 8 + pick_len
    (n_buf,) = _U64.unpack_from(view, off)
    off += 8
    buffers = []
    for _ in range(n_buf):
        (blen,) = _U64.unpack_from(view, off)
        off += 8
        buffers.append(view[off : off + blen])
        off += blen
    return pickle.loads(pick, buffers=buffers)
