"""ctypes binding for the native shm arena store (cpp/shm_store.cc).

One mmap'd tmpfs arena per (session, host): the C side owns metadata (index,
free-list, robust process-shared mutex, LRU eviction, pin counts); Python maps
the same file MAP_SHARED and reads/writes object bytes at the offsets the C
side hands out — zero-copy for consumers, exactly like the file-per-object
backend but with bounded memory and eviction.

(reference capability: src/ray/object_manager/plasma/ — store over dlmalloc'd
shm with LRU eviction_policy.h:159; here arena+offsets instead of fds.)
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "shm_store.cc")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "build")
_LIB = os.path.join(_LIB_DIR, "libshmstore.so")

_build_lock = threading.Lock()
_lib = None

from ray_tpu._private.ray_config import RayConfig

DEFAULT_CAPACITY = RayConfig.get("store_capacity")


class ArenaFullError(Exception):
    """No contiguous run fits even after evicting every unpinned object."""


def _ensure_lib() -> ctypes.CDLL:
    """Build (if missing/stale) and load the native library, once per process."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = os.path.abspath(_SRC)
        lib = os.path.abspath(_LIB)
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            os.makedirs(os.path.dirname(lib), exist_ok=True)
            tmp = lib + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src, "-lpthread"],
                check=True, capture_output=True)
            os.replace(tmp, lib)  # atomic: concurrent builders don't collide
        dll = ctypes.CDLL(lib)
        dll.rtpu_store_open.restype = ctypes.c_void_p
        dll.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        dll.rtpu_store_close.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_create.restype = ctypes.c_int64
        dll.rtpu_store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        dll.rtpu_store_seal.restype = ctypes.c_int
        dll.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_get.restype = ctypes.c_int64
        dll.rtpu_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        dll.rtpu_store_release.restype = ctypes.c_int
        dll.rtpu_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_contains.restype = ctypes.c_int
        dll.rtpu_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_size.restype = ctypes.c_int64
        dll.rtpu_store_size.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_delete.restype = ctypes.c_int
        dll.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_used.restype = ctypes.c_uint64
        dll.rtpu_store_used.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_capacity.restype = ctypes.c_uint64
        dll.rtpu_store_capacity.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_num_objects.restype = ctypes.c_uint32
        dll.rtpu_store_num_objects.argtypes = [ctypes.c_void_p]
        _lib = dll
        return dll


class _ArenaObject:
    """A pinned view into the arena; unpins on GC (plasma release)."""

    __slots__ = ("buf", "_store", "_oid", "_released")

    def __init__(self, buf: memoryview, store: "ArenaStore", oid: str):
        self.buf = buf
        self._store = store
        self._oid = oid
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.buf = None
            self._store._release(self._oid)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class ArenaStore:
    """Drop-in for ShmObjectStore, backed by the native arena.

    All processes of a session on one host share one arena file; `get`
    returns pinned zero-copy views, `put_parts` may evict LRU sealed objects
    to make room (the file backend instead grows until tmpfs fills).
    """

    def __init__(self, session_id: str, capacity: int = 0):
        from ray_tpu._private.object_store import spill_dir_for

        self.session_id = session_id
        self.path = os.path.join("/dev/shm", f"rtpu_{session_id}_arena")
        self.spill_dir = spill_dir_for(session_id)
        self._dll = _ensure_lib()
        cap = capacity or DEFAULT_CAPACITY
        self._handle = self._dll.rtpu_store_open(self.path.encode(), cap, 1)
        if not self._handle:
            raise OSError(f"cannot open shm arena at {self.path}")
        f = open(self.path, "r+b")
        try:
            total = os.fstat(f.fileno()).st_size
            self._mm = mmap.mmap(f.fileno(), total)
        finally:
            f.close()
        self._lock = threading.Lock()

    # -- interface shared with ShmObjectStore ------------------------------

    def _spill_path(self, object_hex: str) -> str:
        return os.path.join(self.spill_dir, object_hex)

    def put_parts(self, object_hex: str, parts, total: int) -> str:
        """Returns the tier the object landed on ("shm" | "spill"),
        matching ShmObjectStore.put_parts."""
        oid = object_hex.encode()
        off = self._dll.rtpu_store_create(self._handle, oid, max(total, 1))
        if off == -2:
            return "shm"  # already present (idempotent re-put)
        if off < 0:
            # no room even after eviction: create straight in the spill tier
            os.makedirs(self.spill_dir, exist_ok=True)
            tmp = self._spill_path(object_hex) + ".tmp"
            with open(tmp, "wb") as f:
                for p in parts:
                    f.write(p)
            os.replace(tmp, self._spill_path(object_hex))
            return "spill"
        pos = off
        for p in parts:
            n = len(p) if isinstance(p, bytes) else p.nbytes
            self._mm[pos:pos + n] = p
            pos += n
        rc = self._dll.rtpu_store_seal(self._handle, oid)
        if rc != 0:
            raise OSError(f"seal({object_hex}) failed: {rc}")
        return "shm"

    def get(self, object_hex: str):
        oid = object_hex.encode()
        size = ctypes.c_uint64()
        off = self._dll.rtpu_store_get(self._handle, oid, ctypes.byref(size))
        if off < 0:
            # spill-tier fallback (mmap'd from disk)
            try:
                f = open(self._spill_path(object_hex), "rb")
            except FileNotFoundError:
                raise FileNotFoundError(
                    f"object {object_hex} not in arena (evicted?)") from None
            from ray_tpu._private.object_store import PlasmaObject

            n = os.fstat(f.fileno()).st_size
            mm = mmap.mmap(f.fileno(), n, prot=mmap.PROT_READ)
            return PlasmaObject(memoryview(mm), mm, f)
        view = memoryview(self._mm)[off:off + size.value]
        return _ArenaObject(view, self, object_hex)

    def contains(self, object_hex: str) -> bool:
        return (bool(self._dll.rtpu_store_contains(self._handle, object_hex.encode()))
                or os.path.exists(self._spill_path(object_hex)))

    def tier_of(self, object_hex: str) -> "str | None":
        if self._dll.rtpu_store_contains(self._handle, object_hex.encode()):
            return "shm"
        if os.path.exists(self._spill_path(object_hex)):
            return "spill"
        return None

    def size(self, object_hex: str) -> int:
        n = self._dll.rtpu_store_size(self._handle, object_hex.encode())
        if n < 0:
            try:
                return os.stat(self._spill_path(object_hex)).st_size
            except FileNotFoundError:
                raise FileNotFoundError(object_hex) from None
        return n

    def spill(self, object_hex: str) -> bool:
        """Copy an arena object to the disk tier, then drop it from the arena."""
        oid = object_hex.encode()
        size = ctypes.c_uint64()
        off = self._dll.rtpu_store_get(self._handle, oid, ctypes.byref(size))
        if off < 0:
            return False
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            tmp = self._spill_path(object_hex) + ".tmp"
            with open(tmp, "wb") as f:
                f.write(self._mm[off:off + size.value])
            os.replace(tmp, self._spill_path(object_hex))
        finally:
            self._dll.rtpu_store_release(self._handle, oid)
        self._dll.rtpu_store_delete(self._handle, oid)
        return True

    def delete(self, object_hex: str) -> None:
        self._dll.rtpu_store_delete(self._handle, object_hex.encode())
        try:
            os.unlink(self._spill_path(object_hex))
        except FileNotFoundError:
            pass

    def cleanup_session(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        import shutil

        shutil.rmtree(self.spill_dir, ignore_errors=True)

    # -- arena-specific ----------------------------------------------------

    def _release(self, object_hex: str) -> None:
        self._dll.rtpu_store_release(self._handle, object_hex.encode())

    def used(self) -> int:
        return self._dll.rtpu_store_used(self._handle)

    def capacity(self) -> int:
        return self._dll.rtpu_store_capacity(self._handle)

    def num_objects(self) -> int:
        return self._dll.rtpu_store_num_objects(self._handle)
