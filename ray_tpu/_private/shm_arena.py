"""ctypes binding for the native shm arena store (cpp/shm_store.cc).

One mmap'd tmpfs arena per (session, host): the C side owns metadata
(hash-indexed object table, free-list, robust process-shared mutex, per-pid
pin registry); Python maps the same file MAP_SHARED and reads/writes object
bytes at the offsets the C side hands out — zero-copy for consumers, exactly
like the file-per-object backend but with bounded memory and eviction.

Eviction here never drops the only copy of an object: when a put needs room,
the LRU sealed+unpinned victim is SPILLED to the disk tier first (reusing the
two-tier layout the file backend already has), then freed from the arena —
the plasma analogue would be eviction + restore-from-external-storage
(reference: src/ray/object_manager/plasma/ — store over dlmalloc'd shm with
LRU eviction_policy.h:159; here arena+offsets instead of fds). Pins held by
processes that died are reaped from the shared pin registry so a SIGKILLed
reader can never wedge eviction.
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import subprocess
import threading

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "shm_store.cc")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "build")
_LIB = os.path.join(_LIB_DIR, "libshmstore.so")

_build_lock = threading.Lock()
_lib = None

from ray_tpu._private.constants import SHM_DIR, SHM_SESSION_PREFIX
from ray_tpu._private.ray_config import RayConfig

# Puts at or above this size bypass the mmap store and pwrite() instead:
# storing through the mapping faults fresh tmpfs pages one at a time, a
# syscall copies and allocates them in bulk. Below it, syscall overhead
# dominates and the mmap copy wins.
_BULK_WRITE_MIN = 256 * 1024


class ArenaFullError(Exception):
    """No contiguous run fits even after evicting every unpinned object."""


def _ensure_lib() -> ctypes.CDLL:
    """Build (if missing/stale) and load the native library, once per process.
    Raises on a missing/broken toolchain — make_object_store catches that and
    falls back to the file backend rather than failing ray_tpu.init()."""
    global _lib
    if _lib is not None:
        return _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        src = os.path.abspath(_SRC)
        lib = os.path.abspath(_LIB)
        if (not os.path.exists(lib)
                or os.path.getmtime(lib) < os.path.getmtime(src)):
            os.makedirs(os.path.dirname(lib), exist_ok=True)
            tmp = lib + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, src, "-lpthread"],
                check=True, capture_output=True)
            os.replace(tmp, lib)  # atomic: concurrent builders don't collide
        dll = ctypes.CDLL(lib)
        dll.rtpu_store_open.restype = ctypes.c_void_p
        dll.rtpu_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        dll.rtpu_store_close.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_create.restype = ctypes.c_int64
        dll.rtpu_store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        dll.rtpu_store_create_noevict.restype = ctypes.c_int64
        dll.rtpu_store_create_noevict.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        dll.rtpu_store_seal.restype = ctypes.c_int
        dll.rtpu_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_get.restype = ctypes.c_int64
        dll.rtpu_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.POINTER(ctypes.c_uint64)]
        dll.rtpu_store_release.restype = ctypes.c_int
        dll.rtpu_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_contains.restype = ctypes.c_int
        dll.rtpu_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_size.restype = ctypes.c_int64
        dll.rtpu_store_size.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_delete.restype = ctypes.c_int
        dll.rtpu_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_lru_victim.restype = ctypes.c_int
        dll.rtpu_store_lru_victim.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        dll.rtpu_store_reap_dead.restype = ctypes.c_int
        dll.rtpu_store_reap_dead.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_release_pid.restype = ctypes.c_int
        dll.rtpu_store_release_pid.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        dll.rtpu_store_used.restype = ctypes.c_uint64
        dll.rtpu_store_used.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_capacity.restype = ctypes.c_uint64
        dll.rtpu_store_capacity.argtypes = [ctypes.c_void_p]
        dll.rtpu_store_num_objects.restype = ctypes.c_uint32
        dll.rtpu_store_num_objects.argtypes = [ctypes.c_void_p]
        _lib = dll
        return dll


class _ArenaObject:
    """A pinned view into the arena; unpins on GC (plasma release)."""

    __slots__ = ("buf", "_store", "_oid", "_released", "__weakref__")

    def __init__(self, buf: memoryview, store: "ArenaStore", oid: str):
        self.buf = buf
        self._store = store
        self._oid = oid
        self._released = False

    def release(self):
        if not self._released:
            self._released = True
            self.buf = None
            self._store._release(self._oid)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class ArenaStore:
    """Drop-in for ShmObjectStore, backed by the native arena.

    All processes of a session on one host share one arena file; `get`
    returns pinned zero-copy views, `put_parts` spills LRU sealed objects to
    the disk tier to make room (the file backend instead grows until tmpfs
    fills). `on_evict` (if set) is called with the list of object ids each
    put pushed down to the spill tier — the CoreWorker/node-agent forward
    that to the GCS so cluster tmpfs accounting and `tier_of` stay truthful.
    """

    def __init__(self, session_id: str, capacity: int = 0):
        from ray_tpu._private.object_store import spill_dir_for

        self.session_id = session_id
        self.prefix = f"{SHM_SESSION_PREFIX}{session_id}_"
        self.path = os.path.join(SHM_DIR, self.prefix + "arena")
        self.spill_dir = spill_dir_for(session_id)
        self._dll = _ensure_lib()
        cap = capacity or RayConfig.get("store_capacity")
        try:
            # plasma-style capping: an arena bigger than tmpfs can hold
            # would SIGBUS writers when pages can't be allocated — cap at
            # 80% of what /dev/shm can actually back right now
            vfs = os.statvfs(SHM_DIR)
            cap = max(1 << 20, min(cap, int(vfs.f_bavail * vfs.f_frsize * 0.8)))
        except OSError:
            pass
        self._handle = self._dll.rtpu_store_open(self.path.encode(), cap, 1)
        if not self._handle:
            raise OSError(f"cannot open shm arena at {self.path}")
        f = open(self.path, "r+b")
        try:
            total = os.fstat(f.fileno()).st_size
            self._mm = mmap.mmap(f.fileno(), total)
        except Exception:
            f.close()
            self._dll.rtpu_store_close(self._handle)
            raise
        self._file = f  # kept open: large puts pwrite() at the C-side offset
        self._lock = threading.Lock()
        self.on_evict = None  # callable(list[str]) | None
        self.evictions = 0  # objects THIS process spilled to make room
        import weakref

        self._views = weakref.WeakSet()  # live pinned views of this process

    # -- interface shared with ShmObjectStore ------------------------------

    def _spill_path(self, object_hex: str) -> str:
        return os.path.join(self.spill_dir, object_hex)

    def put_parts(self, object_hex: str, parts, total: int) -> str:
        """Create+seal an object from pre-serialized parts. Returns the tier
        it actually landed on ("shm" | "spill"), matching ShmObjectStore.
        Never drops data to make room: LRU victims are spilled to disk."""
        oid = object_hex.encode()
        size = max(total, 1)
        evicted: list[str] = []
        try:
            off = self._dll.rtpu_store_create_noevict(self._handle, oid, size)
            while off == -1:  # no contiguous run: spill the LRU victim
                victim = ctypes.create_string_buffer(48)
                if self._dll.rtpu_store_lru_victim(self._handle, victim) == 0:
                    vic = victim.value.decode()
                    try:
                        if self.spill(vic):
                            evicted.append(vic)
                    except OSError:
                        logger.exception("evict-to-spill of %s failed", vic)
                        break  # disk trouble: fall through to spill-tier put
                elif self._dll.rtpu_store_reap_dead(self._handle) > 0:
                    pass  # orphaned pins released, space may be free: retry
                else:
                    break  # everything resident is live-pinned
                off = self._dll.rtpu_store_create_noevict(self._handle, oid, size)
            if off == -2:
                # already present: report where the object actually lives
                # (it may sit in the spill tier) so GCS tmpfs accounting
                # isn't inflated by re-puts
                tier = self.tier_of(object_hex)
                if tier is None:
                    # deferred-delete ghost: the old entry is kDeleting
                    # (readers still pinned) so the arena refuses the id,
                    # but the object is logically gone. Preserve the
                    # re-put's bytes in the spill tier — claiming "shm"
                    # here would silently lose the only copy.
                    self._write_spill(object_hex, parts)
                    return "spill"
                return tier
            if off < 0:
                # -4 larger than the arena, -1 unplaceable, -3 index full:
                # create straight in the spill tier
                self._write_spill(object_hex, parts)
                return "spill"
            pos = off
            if size >= _BULK_WRITE_MIN:
                # bulk pwrite: storing through the mmap faults each fresh
                # tmpfs page individually (~3x slower than the file backend
                # at 4 MiB); one write syscall allocates pages in bulk
                # in-kernel. tmpfs is the page cache, so the MAP_SHARED
                # views other processes hold stay coherent.
                fd = self._file.fileno()
                for p in parts:
                    mv = p if isinstance(p, bytes) else memoryview(p).cast("B")
                    sent = os.pwrite(fd, mv, pos)
                    while sent < len(mv):  # short write (rare on tmpfs)
                        sent += os.pwrite(fd, memoryview(mv)[sent:], pos + sent)
                    pos += len(mv)
            else:
                for p in parts:
                    n = len(p) if isinstance(p, bytes) else p.nbytes
                    self._mm[pos:pos + n] = p
                    pos += n
            rc = self._dll.rtpu_store_seal(self._handle, oid)
            if rc != 0:
                raise OSError(f"seal({object_hex}) failed: {rc}")
            return "shm"
        finally:
            self._note_evicted(evicted)

    def _write_spill(self, object_hex: str, parts) -> None:
        # pid-suffixed temp name: two processes spilling the same object
        # must not corrupt each other's atomic rename
        os.makedirs(self.spill_dir, exist_ok=True)
        dst = self._spill_path(object_hex)
        tmp = dst + f".tmp{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                for p in parts:
                    f.write(p)
            os.replace(tmp, dst)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _note_evicted(self, evicted: list) -> None:
        if not evicted:
            return
        self.evictions += len(evicted)
        cb = self.on_evict
        if cb is not None:
            try:
                cb(list(evicted))
            except Exception:
                logger.exception("on_evict hook failed")

    def get(self, object_hex: str):
        oid = object_hex.encode()
        size = ctypes.c_uint64()
        off = self._dll.rtpu_store_get(self._handle, oid, ctypes.byref(size))
        if off < 0:
            # spill-tier fallback (mmap'd from disk)
            try:
                f = open(self._spill_path(object_hex), "rb")
            except FileNotFoundError:
                raise FileNotFoundError(
                    f"object {object_hex} not in arena (evicted?)") from None
            from ray_tpu._private.object_store import PlasmaObject

            try:
                n = os.fstat(f.fileno()).st_size
                mm = mmap.mmap(f.fileno(), n, prot=mmap.PROT_READ)
            except BaseException:
                f.close()  # mmap of an empty/torn spill file raises
                raise
            return PlasmaObject(memoryview(mm), mm, f)
        view = memoryview(self._mm)[off:off + size.value]
        obj = _ArenaObject(view, self, object_hex)
        self._views.add(obj)
        return obj

    def contains(self, object_hex: str) -> bool:
        return (bool(self._dll.rtpu_store_contains(self._handle, object_hex.encode()))
                or os.path.exists(self._spill_path(object_hex)))

    def tier_of(self, object_hex: str) -> "str | None":
        if self._dll.rtpu_store_contains(self._handle, object_hex.encode()):
            return "shm"
        if os.path.exists(self._spill_path(object_hex)):
            return "spill"
        return None

    def size(self, object_hex: str) -> int:
        n = self._dll.rtpu_store_size(self._handle, object_hex.encode())
        if n < 0:
            try:
                return os.stat(self._spill_path(object_hex)).st_size
            except FileNotFoundError:
                raise FileNotFoundError(object_hex) from None
        return n

    def spill(self, object_hex: str) -> bool:
        """Copy an arena object to the disk tier, then drop it from the arena.

        Known race (predates the arena default, window widened by the put
        evict loop): a concurrent delete() that runs between our pin and the
        _write_spill publish leaves a stale spill file behind — the deleted
        id then reads as tier "spill" and its bytes sit on disk until
        cleanup_session. Nothing dereferences a GCS-freed id, so the cost is
        the leaked file, not wrong data; closing it needs a delete tombstone
        the two-tier layout doesn't have yet."""
        oid = object_hex.encode()
        size = ctypes.c_uint64()
        off = self._dll.rtpu_store_get(self._handle, oid, ctypes.byref(size))
        if off < 0:
            return False
        try:
            self._write_spill(object_hex, [self._mm[off:off + size.value]])
        finally:
            self._dll.rtpu_store_release(self._handle, oid)
        self._dll.rtpu_store_delete(self._handle, oid)
        return True

    def delete(self, object_hex: str) -> None:
        self._dll.rtpu_store_delete(self._handle, object_hex.encode())
        try:
            os.unlink(self._spill_path(object_hex))
        except FileNotFoundError:
            pass

    def cleanup_session(self) -> None:
        """Unlink the arena segment, the spill dir, and any per-object tmpfs
        files a file-backend fallback process of the same session created."""
        try:
            names = os.listdir(SHM_DIR)
        except FileNotFoundError:
            names = []
        for name in names:
            if name.startswith(self.prefix):
                try:
                    os.unlink(os.path.join(SHM_DIR, name))
                except OSError:
                    pass
        import shutil

        shutil.rmtree(self.spill_dir, ignore_errors=True)

    # -- arena-specific ----------------------------------------------------

    def _release(self, object_hex: str) -> None:
        self._dll.rtpu_store_release(self._handle, object_hex.encode())

    def release_pid_pins(self) -> int:
        """Release every pin this process still holds (clean-exit path).
        Outstanding views release themselves by oid first — that needs no
        registry attribution, so it works even for pins taken while the
        shared registry was full — then the pid sweep drops whatever
        recorded edges remain (views lost without GC)."""
        n = 0
        for v in list(self._views):
            if not v._released:
                v.release()
                n += 1
        return n + self._dll.rtpu_store_release_pid(self._handle, os.getpid())

    def reap_dead_pins(self) -> int:
        """Release pins whose holder process no longer exists."""
        return self._dll.rtpu_store_reap_dead(self._handle)

    def used(self) -> int:
        return self._dll.rtpu_store_used(self._handle)

    def capacity(self) -> int:
        return self._dll.rtpu_store_capacity(self._handle)

    def num_objects(self) -> int:
        return self._dll.rtpu_store_num_objects(self._handle)
