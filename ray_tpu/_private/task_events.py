"""Task/profile event buffering and chrome-trace timeline export.

Reference capability: workers emit ProfileEvents batched by TaskEventBuffer
to the GCS task-event store, exported by `ray timeline` as a chrome trace
(reference: src/ray/core_worker/profile_event.h,
src/ray/core_worker/task_event_buffer.h, src/ray/gcs/gcs_task_manager.h;
gated by RAY_CONFIG enable_timeline, ray_config_def.h:615).

Design: each process keeps a bounded buffer of timeline spans; the
CoreWorker's background flusher ships batches to the GCS piggybacked on the
refcount-delta channel, the GCS appends them to its task-event deque, and
``ray_tpu timeline`` renders everything as chrome://tracing JSON
(one row per worker process).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager

from .ray_config import RayConfig

_lock = threading.Lock()
_buffer: collections.deque = collections.deque(maxlen=10000)
_enabled: bool | None = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = RayConfig.instance().enable_timeline
        with _lock:
            _buffer.__init__(maxlen=RayConfig.instance().task_events_max)
    return _enabled


def emit(event: str, *, task_id: str = "", name: str = "",
         start: float | None = None, end: float | None = None,
         **extra) -> None:
    """Record one completed span (start/end in time.time() seconds)."""
    if not enabled():
        return
    rec = {"event": event, "task_id": task_id, "name": name,
           "pid": os.getpid(), "start": start, "end": end}
    if extra:
        rec.update(extra)
    with _lock:
        _buffer.append(rec)


@contextmanager
def span(event: str, *, task_id: str = "", name: str = "", **extra):
    if not enabled():
        yield
        return
    t0 = time.time()
    try:
        yield
    finally:
        emit(event, task_id=task_id, name=name, start=t0, end=time.time(),
             **extra)


def drain() -> list:
    """Pop all buffered events (called by the worker's flush loop)."""
    with _lock:
        out = list(_buffer)
        _buffer.clear()
    return out


# ------------------------------------------------------- flight recorder

# In-process ring of the last N serve request summaries (always-on, unlike
# span sampling): a slow request can be explained after the fact without
# sampling luck. The worker flusher ships new entries to the GCS request
# log at the metrics cadence; the ring itself answers local inspection.
_req_lock = threading.Lock()
_req_ring: collections.deque | None = None
_req_seq = 0
_req_flushed_seq = 0


def _ring() -> collections.deque:
    global _req_ring
    if _req_ring is None:
        _req_ring = collections.deque(maxlen=max(
            1, RayConfig.instance().serve_flight_recorder_size))
    return _req_ring


def record_request(summary: dict) -> None:
    """Append one request summary ({request_id, path, phases, ...}) to the
    flight-recorder ring."""
    global _req_seq
    rec = dict(summary)
    rec.setdefault("pid", os.getpid())
    with _req_lock:
        _req_seq += 1
        rec["seq"] = _req_seq
        _ring().append(rec)


def recent_requests() -> list:
    """The ring's current contents, oldest first (local inspection/tests)."""
    with _req_lock:
        return [dict(r) for r in (_req_ring or ())]


def drain_request_log() -> list:
    """Entries recorded since the last drain that are STILL in the ring
    (older ones already rotated out — exactly the last-N semantics). Called
    by the worker's telemetry flusher."""
    global _req_flushed_seq
    with _req_lock:
        out = [dict(r) for r in (_req_ring or ())
               if r["seq"] > _req_flushed_seq]
        if out:
            _req_flushed_seq = out[-1]["seq"]
    return out


def reset_request_log() -> None:
    """Test helper: drop the ring so a new RayConfig size takes effect."""
    global _req_ring, _req_seq, _req_flushed_seq
    with _req_lock:
        _req_ring = None
        _req_seq = 0
        _req_flushed_seq = 0


def normalize_events(events: list) -> list:
    """Normalize GCS-side completion records (ts only) into zero-length
    spans so every export path renders them identically — the chrome-trace
    renderer drops events without start/end."""
    for ev in events:
        if "start" not in ev and "ts" in ev:
            ev["start"] = ev["ts"]
            ev["end"] = ev["ts"]
            # cluster events carry an etype; task completions don't
            ev.setdefault("event", ev.get("etype") or "task:done")
            if ev.get("etype"):
                ev.setdefault("name", ev["etype"])
            ev.setdefault("worker_id", ev.get("worker", ""))
    return events


def export_chrome_trace(events: list, filename: str,
                        worker_names: dict | None = None) -> None:
    """One exporter for CLI / dashboard / api.timeline: normalize + render
    + write."""
    with open(filename, "w") as f:
        f.write(to_chrome_trace(normalize_events(list(events)),
                                worker_names))


def worker_display_names(workers: list, actors: dict) -> dict:
    """wid → human label for timeline rows: actor workers are labeled with
    the actor's class/name from the GCS actor table instead of a bare
    pid/wid, so e.g. compiled-DAG exec-loop rows read as `Stage:my_actor`
    rather than an opaque id. `workers` is the list_workers RPC rows,
    `actors` the cluster_state actor map."""
    names: dict = {}
    for w in workers or ():
        aid = w.get("actor_id")
        if not aid:
            continue
        info = (actors or {}).get(aid) or {}
        cls = info.get("class") or "Actor"
        label = (f"{cls}:{info['name']}" if info.get("name")
                 else f"{cls}@{aid[:8]}")
        names[w["wid"]] = f"{label} (pid {w.get('pid')})"
    return names


def fetch_worker_names(rpc) -> dict:
    """worker_display_names over any GCS request/reply callable (driver
    worker, dashboard client, CLI client). Labels are decoration: any RPC
    failure yields {} rather than failing the export."""
    try:
        return worker_display_names(
            rpc({"type": "list_workers"}).get("workers", []),
            rpc({"type": "cluster_state"})["state"].get("actors", {}))
    except Exception:
        return {}


def to_chrome_trace(events: list, worker_names: dict | None = None) -> str:
    """Render GCS-collected events as chrome://tracing 'traceEvents' JSON.

    Rows: one per (worker-id, pid) — except compiled-DAG step spans, which
    carry a `dag_id` and are grouped under one row per DAG (tid = DAG node)
    so a pipeline's steps line up regardless of which worker ran them, and
    serve/PD request spans, which carry a `request_id` and group under one
    row per request (tid = emitting pid) so one request's cross-process
    phases line up as a timeline. Durations become complete ('X') events
    with microsecond timestamps, matching what chrome://tracing / Perfetto
    ingests from the reference's `ray timeline` output.
    """
    worker_names = worker_names or {}
    trace = []
    for ev in events:
        if ev.get("start") is None:
            continue
        wid = ev.get("worker_id", "") or str(ev.get("pid", 0))
        if ev.get("dag_id"):
            row = f"dag:{ev['dag_id']}"
            tid = ev.get("node") or ev.get("pid", 0)
        elif ev.get("request_id"):
            row = f"req:{ev['request_id']}"
            tid = ev.get("pid", 0)
        elif ev.get("etype"):
            # control-plane cluster events (node/actor/PG lifecycle): one
            # row per node so a node's control transitions line up next to
            # the task rows of the workers it hosted
            row = f"ctrl:{ev.get('node') or 'cluster'}"
            tid = ev["etype"]
        else:
            row = worker_names.get(wid, wid)
            tid = ev.get("pid", 0)
        trace.append({
            "name": ev.get("name") or ev.get("event", ""),
            "cat": ev.get("event", "task"),
            "ph": "X",
            "ts": ev["start"] * 1e6,
            "dur": max(0.0, ((ev.get("end") or ev["start"]) - ev["start"])) * 1e6,
            "pid": row,
            "tid": tid,
            "args": {k: v for k, v in ev.items()
                     if k not in ("start", "end", "name", "event", "pid")},
        })
    return json.dumps({"traceEvents": trace, "displayTimeUnit": "ms"})
