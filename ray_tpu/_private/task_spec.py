"""Typed task/actor/placement-group specifications.

(reference: src/ray/common/task/task_spec.h — TaskSpecification wraps the
wire message with typed accessors and VALIDATES at construction, so a
malformed submission fails at the caller with a clear error instead of
surfacing as a scheduler crash three hops later. The wire format here
stays the framed-protocol dict — these dataclasses are the typed front:
`validate_*` runs at the submission boundary, and the dataclass views give
tooling a stable schema for introspection.)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

from ray_tpu._private.constants import EXEC_LOOP_METHOD  # noqa: F401 — re-export:
# the exec-loop method name moved to the shared constants module; existing
# importers (worker.py, dag/channel_execution.py historical sites) keep
# resolving it from here.

VALID_STRATEGY_KINDS = ("pg", "node_affinity", "node_label")
_MAX_NAME = 512


class SpecError(ValueError):
    """A malformed submission, reported at the caller."""


def _check_resources(res: Any, where: str) -> None:
    if res is None:
        return
    if not isinstance(res, dict):
        raise SpecError(f"{where}: resources must be a dict, got "
                        f"{type(res).__name__}")
    for k, v in res.items():
        if not isinstance(k, str) or not k:
            raise SpecError(f"{where}: resource names must be non-empty "
                            f"strings, got {k!r}")
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise SpecError(f"{where}: resource {k!r} must be numeric, got "
                            f"{type(v).__name__}")
        if not math.isfinite(v):
            # NaN/inf would survive `v < 0` (False for NaN) and then blow
            # up inside the GCS fixed-point quantization under the
            # scheduler lock — the exact crash this boundary exists to stop.
            raise SpecError(f"{where}: resource {k!r} must be finite, got {v}")
        if v < 0:
            raise SpecError(f"{where}: resource {k!r} is negative ({v})")
        if k in ("TPU", "GPU") and float(v) != int(v) and v > 1:
            raise SpecError(f"{where}: accelerator {k!r} must be fractional "
                            f"<= 1 or a whole number, got {v}")


def _check_strategy(strategy: Any, where: str) -> None:
    if strategy is None:
        return
    if not isinstance(strategy, dict) or "kind" not in strategy:
        raise SpecError(f"{where}: strategy must be a dict with a 'kind'")
    kind = strategy["kind"]
    if kind not in VALID_STRATEGY_KINDS:
        raise SpecError(f"{where}: unknown strategy kind {kind!r} "
                        f"(valid: {VALID_STRATEGY_KINDS})")
    if kind == "pg":
        if not strategy.get("pg_id"):
            raise SpecError(f"{where}: pg strategy needs pg_id")
        b = strategy.get("bundle", -1)
        if not isinstance(b, int) or b < -1:
            raise SpecError(f"{where}: pg bundle index must be an int >= -1")
    if kind == "node_affinity" and not strategy.get("node_id"):
        raise SpecError(f"{where}: node_affinity strategy needs node_id")
    if kind == "node_label":
        hard = strategy.get("hard", {})
        if not isinstance(hard, dict):
            raise SpecError(f"{where}: node_label 'hard' must be a dict")


def _check_common(spec: dict, where: str) -> None:
    if not spec.get("task_id"):
        raise SpecError(f"{where}: missing task_id")
    name = spec.get("name")
    if name is not None and (not isinstance(name, str)
                             or len(name) > _MAX_NAME):
        raise SpecError(f"{where}: name must be a string under "
                        f"{_MAX_NAME} chars")
    _check_resources(spec.get("resources"), where)
    _check_strategy(spec.get("strategy"), where)


def validate_task(spec: dict) -> dict:
    """Validate a task submission dict; returns it unchanged on success."""
    where = f"task {spec.get('name') or spec.get('task_id')}"
    _check_common(spec, where)
    nr = spec.get("num_returns", 1)
    if nr != "streaming" and (not isinstance(nr, int) or nr < 0):
        raise SpecError(f"{where}: num_returns must be an int >= 0 or "
                        f"'streaming', got {nr!r}")
    mr = spec.get("max_retries", 0)
    if not isinstance(mr, int) or mr < -1:
        raise SpecError(f"{where}: max_retries must be an int >= -1")
    if not isinstance(spec.get("deps", []), (list, tuple)):
        raise SpecError(f"{where}: deps must be a list")
    return spec


def validate_actor(spec: dict) -> dict:
    where = f"actor {spec.get('name') or spec.get('actor_id')}"
    _check_common(spec, where)
    if not spec.get("actor_id"):
        raise SpecError(f"{where}: missing actor_id")
    mr = spec.get("max_restarts", 0)
    if not isinstance(mr, int) or mr < -1:
        raise SpecError(f"{where}: max_restarts must be an int >= -1")
    mtr = spec.get("max_task_retries", 0)
    if not isinstance(mtr, int) or mtr < -1:
        raise SpecError(f"{where}: max_task_retries must be an int >= -1")
    mc = spec.get("max_concurrency", 1)
    if not isinstance(mc, int) or mc < 1:
        raise SpecError(f"{where}: max_concurrency must be an int >= 1")
    return spec


def validate_pg(spec: dict) -> dict:
    where = f"placement group {spec.get('name') or spec.get('pg_id')}"
    if not spec.get("pg_id"):
        raise SpecError(f"{where}: missing pg_id")
    bundles = spec.get("bundles")
    if not isinstance(bundles, (list, tuple)) or not bundles:
        raise SpecError(f"{where}: bundles must be a non-empty list")
    for i, b in enumerate(bundles):
        _check_resources(b, f"{where} bundle[{i}]")
        if not b:
            raise SpecError(f"{where}: bundle[{i}] is empty")
    from ray_tpu._private.pg_policy import STRATEGIES

    strat = spec.get("strategy", "PACK")
    if strat not in STRATEGIES:
        raise SpecError(f"{where}: unknown PG strategy {strat!r} "
                        f"(valid: {sorted(STRATEGIES)})")
    return spec


# --------------------------------------------------------- dataclass views


@dataclasses.dataclass(frozen=True)
class TaskSpec:
    """Typed read view over a task wire dict."""

    task_id: str
    name: str | None
    resources: dict
    num_returns: int | str
    max_retries: int
    deps: tuple
    strategy: dict | None
    language: str
    runtime_env_hash: str

    @classmethod
    def from_wire(cls, spec: dict) -> "TaskSpec":
        validate_task(spec)
        return cls(task_id=spec["task_id"], name=spec.get("name"),
                   resources=dict(spec.get("resources") or {}),
                   num_returns=spec.get("num_returns", 1),
                   max_retries=spec.get("max_retries", 0),
                   deps=tuple(spec.get("deps") or ()),
                   strategy=spec.get("strategy"),
                   language=spec.get("lang", "py"),
                   runtime_env_hash=spec.get("renv_hash", ""))


@dataclasses.dataclass(frozen=True)
class ActorSpec:
    actor_id: str
    name: str | None
    resources: dict
    max_restarts: int
    max_task_retries: int
    max_concurrency: int
    strategy: dict | None

    @classmethod
    def from_wire(cls, spec: dict) -> "ActorSpec":
        validate_actor(spec)
        return cls(actor_id=spec["actor_id"], name=spec.get("name"),
                   resources=dict(spec.get("resources") or {}),
                   max_restarts=spec.get("max_restarts", 0),
                   max_task_retries=spec.get("max_task_retries", 0),
                   max_concurrency=spec.get("max_concurrency", 1),
                   strategy=spec.get("strategy"))
