"""CoreWorker: the per-process runtime library embedded in driver and workers.

TPU-native analogue of the reference's core_worker
(reference: src/ray/core_worker/core_worker.h:170 — Put:485, Get:661,
Wait:701, SubmitTask:858, CreateActor:883, SubmitActorTask:940,
ExecuteTask:1482). One instance per process; the driver embeds one too (same
key inversion as the reference: the driver is a peer, not a thin client).
"""

from __future__ import annotations

import itertools
import os
import queue
import sys
import threading
import time
import traceback
from typing import Any, Sequence

from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ActorID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_store import make_object_store
from ray_tpu._private.protocol import ConnectionClosed, connect_address
from ray_tpu._private.constants import (EXEC_LOOP_METHOD,
                                        TENSOR_TRANSPORT_ATTR)
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayTaskError,
    RayTpuError,
    TaskCancelledError,
    WorkerCrashedError,
)

from ray_tpu._private.ray_config import RayConfig as _RayConfig

INLINE_LIMIT = _RayConfig.get("inline_object_limit")
ARGS_INLINE_LIMIT = 4 * INLINE_LIMIT
MAX_RECON_ATTEMPTS = 4


# the process's CoreWorker, for ObjectRef lifecycle hooks (None in local
# mode and before init; distinct from _global_worker which is worker-only)
_ref_tracker = None

# thread-local capture: while serializing a value, ObjectRef.__reduce__
# appends every ref pickled inside, so stored containers can declare the
# refs they keep alive (reference: the serializer's contained-object-ids)
_reduce_capture = threading.local()


def _serialize_capturing(fn, *args):
    """Run a serialization call, returning (result, contained_ref_hexes)."""
    prev = getattr(_reduce_capture, "refs", None)
    _reduce_capture.refs = []
    try:
        out = fn(*args)
        return out, list(dict.fromkeys(_reduce_capture.refs))
    finally:
        _reduce_capture.refs = prev


def _trace_field() -> dict:
    """``{"trace_ctx": ...}`` for an outgoing spec when a trace is active
    in this task/thread, else ``{}`` (tracing off or no open trace)."""
    from ray_tpu.util import tracing

    ctx = tracing.inject()
    return {"trace_ctx": ctx} if ctx else {}


class ObjectRef:
    """Handle to a (possibly pending) remote object. Refcounted: creating one
    registers a local reference, GC drops it; when a process's last local
    reference to an oid disappears the GCS is told, and an object whose
    references are all gone is freed cluster-wide.

    (reference: python/ray/includes/object_ref.pxi:37 + the distributed
    ReferenceCounter, src/ray/core_worker/reference_counter.h:43 — here the
    count is GCS-arbitered rather than owner-distributed.)
    """

    __slots__ = ("_hex", "_tracked")

    def __init__(self, hex_id: str):
        self._hex = hex_id
        tracker = _ref_tracker
        self._tracked = tracker is not None and tracker.incref(hex_id)

    def hex(self) -> str:
        return self._hex

    def __repr__(self):
        return f"ObjectRef({self._hex[:12]}…)"

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._hex == self._hex

    def __hash__(self):
        return hash(("ObjectRef", self._hex))

    def __reduce__(self):
        cap = getattr(_reduce_capture, "refs", None)
        if cap is not None:
            cap.append(self._hex)
        return (ObjectRef, (self._hex,))

    def __del__(self):
        if self._tracked:
            tracker = _ref_tracker
            if tracker is not None:
                try:
                    tracker.decref(self._hex)
                except Exception:
                    pass  # interpreter/worker teardown


class ObjectRefGenerator:
    """Iterator over the ObjectRefs of a `num_returns="streaming"` task;
    refs arrive as the producer yields, with producer-side backpressure.

    (reference: python/ray/_raylet.pyx:299 ObjectRefGenerator /
    _private/object_ref_generator.py — the substrate of Ray Data map tasks.)
    """

    def __init__(self, task_id: str, worker: "CoreWorker"):
        self._task_id = task_id
        self._worker = worker
        self._index = 0
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self.next_item()

    def next_item(self, timeout: float = 86400.0) -> ObjectRef:
        """next() with an explicit timeout: raises GetTimeoutError if the
        producer yields nothing in time (a hung — not dead — producer
        blocks plain next() indefinitely, like the reference's generators)."""
        if self._done:
            raise StopIteration
        reply = self._worker.rpc(
            {"type": "stream_next", "task_id": self._task_id,
             "index": self._index}, timeout=timeout)
        if reply.get("done"):
            self._done = True
            err = reply.get("error")
            if err is not None:
                raise ser.loads(err)
            raise StopIteration
        self._index += 1
        # consumption signal releases producer backpressure
        self._worker.send_no_reply(
            {"type": "stream_consumed", "task_id": self._task_id,
             "index": self._index})
        return ObjectRef(reply["oid"])

    def completed(self) -> bool:
        return self._done

    def __del__(self):
        try:
            self._worker.send_no_reply(
                {"type": "stream_release", "task_id": self._task_id})
        except Exception:
            pass


class _RefMarker:
    """Placeholder for a top-level ObjectRef argument; resolved pre-execution."""

    __slots__ = ("hex",)

    def __init__(self, hex_id: str):
        self.hex = hex_id


class _Future:
    __slots__ = ("event", "value")

    def __init__(self):
        self.event = threading.Event()
        self.value = None

    def set(self, value):
        self.value = value
        self.event.set()

    def wait(self, timeout=None):
        if not self.event.wait(timeout):
            raise GetTimeoutError("timed out waiting for reply")
        return self.value


class CoreWorker:
    def __init__(self, address: str, session_id: str | None, kind: str):
        self.kind = kind
        self.wid = WorkerID().hex()
        # named actors are scoped by namespace (reference: ray namespaces).
        # The DRIVER's namespace comes from init(namespace=...); inside a
        # task/actor call the SUBMITTER's namespace (spec["caller_ns"]) is
        # active, so nested named-actor creation/lookup lands where the
        # submitting driver expects.
        self.namespace = os.environ.get("RAY_TPU_NAMESPACE") or "default"
        if address.startswith("/"):
            address = f"unix:{address}"
        self._address = address
        self._disconnecting = False
        self.conn = connect_address(address)
        self._rid = itertools.count(1)
        self._pending: dict[int, _Future] = {}
        self._pending_lock = threading.Lock()
        self.exec_queue: queue.SimpleQueue = queue.SimpleQueue()
        self._memory: dict[str, Any] = {}
        self._plasma_refs: dict[str, Any] = {}
        self._obj_waits: dict[str, _Future] = {}  # oid → outstanding wait future
        self.actors: dict[str, Any] = {}  # actor instances hosted by this process
        self._actor_pools: dict[str, Any] = {}  # actor_id → ThreadPoolExecutor
        self.current_actor_id: str | None = None  # one actor per process
        self._task_ctx = threading.local()  # per-thread: concurrent actors
        self._alive = True
        self.node_id = os.environ.get("RAY_TPU_NODE_ID", "node-0")
        self.host_id = os.environ.get("RAY_TPU_HOST_ID", "host-0")
        self._recv_thread = threading.Thread(target=self._recv_loop, daemon=True, name="cw-recv")
        self._recv_thread.start()
        if session_id is None:
            # joining an existing cluster by address: learn the session first
            session_id = self.rpc({"type": "get_session"})["session_id"]
        self.session_id = session_id
        # this host's store namespace: followers get their own (a real second
        # machine is naturally disjoint; on one box the env keeps it honest)
        self.store = make_object_store(
            os.environ.get("RAY_TPU_STORE_NS", session_id))
        if hasattr(self.store, "on_evict"):
            # arena backend: a put that evict-spills LRU victims to disk
            # must tell the GCS those copies left tmpfs, or its per-host
            # accounting and the object directory's tier info go stale
            self.store.on_evict = self._report_evictions
        self._reported_evictions = 0  # store.evictions already counted
        self._fetcher = None  # lazy ObjectFetcher for cross-host pulls
        self._stream_acks: dict[str, int] = {}  # producing streams: consumed idx
        self._stream_events: dict[str, threading.Event] = {}
        self._stream_cancelled: set[str] = set()
        # this process's runtime-env fingerprint: set at spawn, used by the
        # scheduler to match tasks to compatible workers (reference: worker
        # pool keyed by runtime-env hash, worker_pool.h:280)
        self.renv_hash = ""
        renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
        if renv_json:
            import json as _json

            from ray_tpu.runtime_env import env_hash as _env_hash

            self.renv_hash = _env_hash(_json.loads(renv_json))
        self._renv_cache: dict[str, tuple[dict, str]] = {}
        self.default_runtime_env: dict | None = None  # job-level default
        from ray_tpu._private.accelerators import current_worker_chips
        from ray_tpu._private.ray_config import RayConfig as _RC

        # direct-dispatch plane (reference: leased-worker task submission,
        # normal_task_submitter.h:81): workers serve leased callers on a
        # dedicated socket; every process can hold leases as a caller
        self._direct_enabled = _RC.get("direct_dispatch")
        self.direct_server = None
        if kind == "worker" and self._direct_enabled:
            from ray_tpu._private.direct import DirectServer

            self.direct_server = DirectServer(self)
        # owner-side records for direct-task results: oid → entry; results
        # that never leave this process never touch the GCS at all
        self._owned: dict[str, dict] = {}
        self._owned_lock = threading.RLock()
        self._loc_cache: dict[str, tuple] = {}  # oid → (host, size) once ready
        self._status_cache: dict[str, str] = {}  # oid → "ready"|"error"
        self._flight_holds: dict[str, list[str]] = {}  # direct tid → held oids
        self._direct = None  # DirectDispatcher, created lazily on first use
        # deserialized task functions keyed by content sha (or raw blob for
        # legacy specs); shas this process already uploaded to the cluster
        # function store (reference: the worker's function table)
        self._func_cache: dict = {}
        self._shipped_fns: dict[str, float] = {}  # sha → last-verified ts
        self._submit_seq = 0  # every Nth GCS submit is synchronous

        reply = self.rpc({"type": "register", "wid": self.wid, "kind": kind,
                          "pid": os.getpid(), "node_id": self.node_id,
                          "host": self.host_id, "renv_hash": self.renv_hash,
                          "tpu_chips": current_worker_chips(),
                          **({"direct_addr": self.direct_server.address}
                             if self.direct_server else {})})
        if reply.get("ok") is False:
            raise RayTpuError(f"registration rejected: {reply.get('error')}")
        # reference counting: per-process local counts, process-level
        # transitions batched to the GCS (reference: reference_counter.h:43)
        self._local_refs: dict[str, int] = {}
        # reentrant: a cyclic-GC run triggered by an allocation inside
        # incref/decref can finalize an ObjectRef on the same thread, whose
        # __del__ re-enters decref while the lock is held
        self._ref_lock = threading.RLock()
        self._flush_order_lock = threading.Lock()
        self._reconnect_lock = threading.Lock()
        self._ref_deltas: dict[str, int] = {}
        from ray_tpu._private.ray_config import RayConfig

        self._gc_enabled = RayConfig.get("auto_gc")
        self._ref_flush_thread = threading.Thread(
            target=self._ref_flush_loop, daemon=True, name="cw-refs")
        self._ref_flush_thread.start()
        global _ref_tracker
        _ref_tracker = self

    # -------------------------------------------------------------- refcounts

    def _gcs_invisible(self, oid: str) -> bool:
        """True for direct-task results that never left this process: the
        GCS has no entry for them, so ref transitions would be dropped there
        anyway — skipping them keeps the hot path free of GCS traffic."""
        ent = self._owned.get(oid)
        return (ent is not None and not ent.get("published")
                and ent.get("status") != "redirect")

    def incref(self, oid: str) -> bool:
        if not self._gc_enabled:
            return False
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) + 1
            self._local_refs[oid] = n
            if n == 1 and not self._gcs_invisible(oid):
                # first local ref in this process
                self._ref_deltas[oid] = self._ref_deltas.get(oid, 0) + 1
        return True

    def decref(self, oid: str) -> None:
        drop_cache = False
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n <= 0:
                self._local_refs.pop(oid, None)
                if not self._gcs_invisible(oid):
                    self._ref_deltas[oid] = self._ref_deltas.get(oid, 0) - 1
                drop_cache = True
            else:
                self._local_refs[oid] = n
        if drop_cache:
            self._memory.pop(oid, None)
            self._plasma_refs.pop(oid, None)
            self._obj_waits.pop(oid, None)
            with self._owned_lock:
                ent = self._owned.get(oid)
                # in-flight entries stay: the reply handler needs them (they
                # die with the flight if the user already dropped the ref)
                if ent is not None and ent.get("status") != "pending":
                    self._owned.pop(oid, None)

    def _ref_flush_loop(self):
        from ray_tpu._private.ray_config import RayConfig

        cfg = RayConfig.instance()
        last_metrics = 0.0
        while self._alive:
            time.sleep(cfg.ref_flush_interval_s)
            self._flush_ref_deltas()
            now = time.time()
            if now - last_metrics >= cfg.metrics_report_interval_s:
                last_metrics = now
                self._flush_telemetry()

    def _report_evictions(self, oids: list) -> None:
        """on_evict hook (arena backend): fire-and-forget accounting update
        so GCS `tier_of`/tmpfs bookkeeping track local evict-to-spill."""
        try:
            self.send_no_reply({"type": "objects_evicted",
                                "host": self.host_id, "oids": list(oids)})
        except Exception:
            pass  # accounting drift is recoverable; the put must not fail

    def _record_store_metrics(self, _met) -> None:
        """Arena accounting → exported gauges/counter. Gauges carry a host
        tag — each host has its own arena, and an unlabeled series would
        flip-flop between hosts at newest-source-wins aggregation; within
        one host every process reports the same shared-header value. The
        eviction counter is per-process (this process's evict-spills) so
        source summation stays correct."""
        store = self.store
        if not hasattr(store, "used"):
            return  # file backend: no bounded arena to meter
        tags = {"host": self.host_id}
        _met.get_or_create(
            _met.Gauge, "ray_tpu_object_store_used",
            "bytes live in this host's shm arena",
        ).set(float(store.used()), tags=tags)
        _met.get_or_create(
            _met.Gauge, "ray_tpu_object_store_capacity",
            "shm arena data-region capacity in bytes",
        ).set(float(store.capacity()), tags=tags)
        delta = store.evictions - self._reported_evictions
        if delta > 0:
            _met.get_or_create(
                _met.Counter, "ray_tpu_object_store_evictions_total",
                "objects this process evict-spilled from the arena to disk",
            ).inc(delta, tags=tags)
            self._reported_evictions = store.evictions

    def _flush_telemetry(self):
        """Ship user metrics + task/profile events to the GCS (reference:
        task_event_buffer.h batching; metrics agent reporting)."""
        try:
            from ray_tpu._private import task_events as _te
            from ray_tpu.util import metrics as _met

            self._record_store_metrics(_met)
            events = _te.drain()
            if events:
                for ev in events:
                    ev["worker_id"] = self.wid
                self.send_no_reply({"type": "events_report", "events": events})
            reqs = _te.drain_request_log()
            if reqs:
                # serve flight-recorder entries -> the GCS request log
                # (bounded per flush by the ring size: only entries still
                # in the last-N ring ship)
                self.send_no_reply({"type": "request_log_report",
                                    "source": self.wid, "entries": reqs})
            from ray_tpu._private import events as _cev
            cevs = _cev.drain()
            if cevs:
                # controller-side cluster events (serve/train controllers
                # run as actors in this process) -> the GCS event ring
                self.send_no_reply({"type": "cluster_events_report",
                                    "source": self.wid, "events": cevs})
            snap = _met.snapshot()
            if snap:
                self.send_no_reply({"type": "metrics_report",
                                    "source": self.wid, "metrics": snap})
        except ConnectionClosed:
            pass
        except Exception:
            pass  # telemetry must never take down the worker

    def _flush_ref_deltas(self):
        # _flush_order_lock spans snapshot AND send: without it, the periodic
        # flusher could snapshot deltas, get preempted, and an exec thread's
        # pre-task_done flush would see an empty dict and emit task_done
        # before the snapshot's +1s hit the wire (breaking the borrower
        # ordering guarantee in execute_task)
        with self._flush_order_lock:
            with self._ref_lock:
                deltas = dict(self._ref_deltas)
                self._ref_deltas.clear()
            # zero entries still ship: a +1/-1 that cancelled within one
            # flush window must still tell the GCS the object was referenced
            # (and is no longer) — otherwise it can never become freeable
            if deltas:
                try:
                    self.send_no_reply({"type": "ref_delta", "deltas": deltas})
                except ConnectionClosed:
                    pass

    # ------------------------------------------------------------------- rpc

    def rpc(self, msg: dict, timeout: float | None = 120.0) -> dict:
        rid = next(self._rid)
        msg["rid"] = rid
        fut = _Future()
        with self._pending_lock:
            self._pending[rid] = fut
        self.conn.send(msg)
        try:
            return fut.wait(timeout)
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)

    def rpc_async(self, msg: dict) -> _Future:
        rid = next(self._rid)
        msg["rid"] = rid
        fut = _Future()
        with self._pending_lock:
            self._pending[rid] = fut
        self.conn.send(msg)
        return fut

    def send_no_reply(self, msg: dict) -> None:
        self.conn.send(msg)

    def _recv_loop(self):
        try:
            while True:
                msg = self.conn.recv()
                if "rid" in msg and "type" not in msg:
                    with self._pending_lock:
                        fut = self._pending.pop(msg["rid"], None)
                    if fut is not None:
                        fut.set(msg)
                elif msg.get("type") == "exec":
                    self.exec_queue.put(msg["spec"])
                elif msg.get("type") == "exit":
                    self.exec_queue.put(None)
                elif msg.get("type") == "die":
                    # force-cancel: terminate immediately (reference: force-
                    # cancelled tasks kill their executor process)
                    os._exit(1)
                elif msg.get("type") == "kill_actor":
                    if msg["aid"] in self.actors:
                        os._exit(0)
                elif msg.get("type") == "log_line":
                    # remote-host worker logs republished via GCS
                    print(f"({msg['source']}) {msg['line']}", file=sys.stderr)
                elif msg.get("type") == "stream_ack":
                    # consumer progress: release producer backpressure
                    tid = msg["task_id"]
                    self._stream_acks[tid] = max(
                        self._stream_acks.get(tid, 0), msg["consumed"])
                    ev = self._stream_events.get(tid)
                    if ev is not None:
                        ev.set()
                elif msg.get("type") == "dump_stacks":
                    # on-demand live inspection (reference capability:
                    # dashboard reporter's py-spy/memray on-demand profiling)
                    import traceback as _tb

                    frames = sys._current_frames()
                    names = {t.ident: t.name for t in threading.enumerate()}
                    parts = []
                    for tid, frame in frames.items():
                        parts.append(f"--- thread {names.get(tid, tid)} ---")
                        parts.append("".join(_tb.format_stack(frame)))
                    try:
                        self.send_no_reply({"type": "stacks_reply",
                                            "token": msg["token"],
                                            "text": "\n".join(parts)})
                    except ConnectionClosed:
                        pass
                elif msg.get("type") == "profile":
                    # sampling profiler: collect collapsed stacks at `hz`
                    # for `duration_s`, reply via the stacks relay
                    def _profile(m=msg):
                        import collections as _c
                        import traceback as _tb

                        duration = min(float(m.get("duration_s", 5.0)), 60.0)
                        period = 1.0 / max(1.0, min(float(m.get("hz", 50.0)), 200.0))
                        counts: _c.Counter = _c.Counter()
                        samples = 0
                        end = time.monotonic() + duration
                        me = threading.get_ident()
                        while time.monotonic() < end:
                            for tid, frame in sys._current_frames().items():
                                if tid == me:
                                    continue
                                stack = []
                                f = frame
                                while f is not None:
                                    co = f.f_code
                                    stack.append(f"{co.co_name} "
                                                 f"({co.co_filename.rsplit('/', 1)[-1]}"
                                                 f":{f.f_lineno})")
                                    f = f.f_back
                                counts[";".join(reversed(stack))] += 1
                            samples += 1
                            time.sleep(period)
                        lines = [f"{n:6d}  {st}" for st, n in counts.most_common(40)]
                        text = (f"# {samples} samples over {duration:.1f}s "
                                f"(collapsed stacks, hottest first)\n"
                                + "\n".join(lines))
                        try:
                            self.send_no_reply({"type": "stacks_reply",
                                                "token": m["token"],
                                                "text": text})
                        except ConnectionClosed:
                            pass

                    threading.Thread(target=_profile, daemon=True,
                                     name="profiler").start()
                elif msg.get("type") == "free_device_tensors":
                    from ray_tpu.experimental import device_objects

                    device_objects.free_device_tensors(
                        msg.get("tensor_ids", ()), worker=self)
                elif msg.get("type") == "do_export_tensor":
                    # RDT: another process needs one of our HBM tensors —
                    # export runs off the recv thread (device→host copy)
                    def _export(m=msg):
                        from ray_tpu.experimental import device_objects

                        try:
                            oid = device_objects.export_to_store(
                                m["tensor_id"], self)
                            self.send_no_reply(
                                {"type": "export_tensor_done",
                                 "token": m["token"], "oid": oid})
                        except Exception as e:  # noqa: BLE001
                            try:
                                self.send_no_reply(
                                    {"type": "export_tensor_done",
                                     "token": m["token"], "oid": None,
                                     "error": repr(e)})
                            except ConnectionClosed:
                                pass

                    threading.Thread(target=_export, daemon=True,
                                     name="rdt-export").start()
                elif msg.get("type") == "stream_cancel":
                    # consumer released the generator: stop producing
                    tid = msg["task_id"]
                    self._stream_cancelled.add(tid)
                    ev = self._stream_events.get(tid)
                    if ev is not None:
                        ev.set()
                elif msg.get("type") == "lease_revoke":
                    # GCS has pending demand this leased worker could serve
                    if self._direct is not None:
                        try:
                            self._direct.revoke(msg["wid"])
                        except Exception:
                            pass
                elif msg.get("type") == "drain_notice":
                    # this worker's node is DRAINING (preemption notice /
                    # scale-down): record it process-wide so train sessions
                    # observe the "save a grace checkpoint now" flag at the
                    # next step boundary
                    _set_drain(msg)
        except ConnectionClosed:
            if self.kind == "driver" and not self._disconnecting:
                # drivers outlive a GCS restart: retry connect + re-register
                # within the configured window (reference: retryable grpc
                # clients + GCS fault tolerance, retryable_grpc_client.h).
                # If another thread already owns the reconnect, this stale
                # recv thread just exits — it must NOT mark the worker dead.
                if not self._reconnect_lock.acquire(blocking=False):
                    return
                try:
                    if self._try_reconnect():
                        return  # a fresh recv thread owns the new connection
                finally:
                    self._reconnect_lock.release()
            self._alive = False
            self.exec_queue.put(None)
            with self._pending_lock:
                for fut in self._pending.values():
                    fut.set({"ok": False, "error": "connection to GCS lost"})
                self._pending.clear()

    def _try_reconnect(self) -> bool:
        """Dial + re-register on a fresh connection. The register handshake
        runs synchronously on the candidate socket (no recv thread until it
        succeeds), so a drop mid-handshake can't spawn competing reconnect
        loops. Caller holds self._reconnect_lock."""
        from ray_tpu._private.accelerators import current_worker_chips
        from ray_tpu._private.ray_config import RayConfig

        window = RayConfig.get("gcs_reconnect_timeout_s")
        # in-flight RPCs died with the old connection; fail them so callers
        # can retry at their level (their rids are unknown to the new GCS)
        with self._pending_lock:
            for fut in self._pending.values():
                fut.set({"ok": False, "error": "GCS connection reset; retry"})
            self._pending.clear()
        deadline = time.monotonic() + window
        while time.monotonic() < deadline and not self._disconnecting:
            conn = None
            try:
                conn = connect_address(self._address, timeout=2.0)
                rid = next(self._rid)
                conn.sock.settimeout(10.0)
                conn.send({"type": "register", "rid": rid, "wid": self.wid,
                           "kind": self.kind, "pid": os.getpid(),
                           "node_id": self.node_id, "host": self.host_id,
                           "renv_hash": self.renv_hash,
                           "tpu_chips": current_worker_chips()})
                reply = conn.recv()
                while reply.get("rid") != rid:
                    reply = conn.recv()  # skip stray non-handshake frames
                if not reply.get("ok"):
                    conn.close()
                    return False
                conn.sock.settimeout(None)
                self.conn = conn
                self._recv_thread = threading.Thread(
                    target=self._recv_loop, daemon=True, name="cw-recv")
                self._recv_thread.start()
                return True
            except (ConnectionClosed, OSError):
                if conn is not None:
                    try:
                        conn.close()
                    except Exception:
                        pass
                time.sleep(0.2)
        return False

    # ----------------------------------------------------------------- tasks

    def _serialize_args(self, args: tuple, kwargs: dict) -> tuple[dict, list[str]]:
        deps: list[str] = []

        def mark(v):
            if isinstance(v, ObjectRef):
                deps.append(v.hex())
                return _RefMarker(v.hex())
            return v

        marked_args = tuple(mark(a) for a in args)
        marked_kwargs = {k: mark(v) for k, v in kwargs.items()}
        # refs nested inside args (top-level ones became _RefMarkers/deps):
        # the GCS holds them until the task completes
        payload, ref_holds = _serialize_capturing(
            ser.dumps, (marked_args, marked_kwargs))
        spec_part: dict = {}
        if ref_holds:
            spec_part["ref_holds"] = ref_holds
        if len(payload) > ARGS_INLINE_LIMIT:
            oid = ObjectID.for_put().hex()
            tier = self.store.put_parts(oid, [payload], len(payload))
            # pinned: no user ref ever exists for an args blob — the GCS
            # frees it with the task's retained lineage (or at actor death)
            self.send_no_reply({"type": "object_put", "oid": oid, "where": "shm",
                                "size": len(payload), "host": self.host_id,
                                "pin": True, "tier": tier})
            spec_part["args_oid"] = oid
        else:
            spec_part["args"] = payload
        return spec_part, deps

    def _prepare_runtime_env(self, runtime_env) -> tuple[dict, str]:
        """Normalize + package a runtime_env once per distinct input
        (reference: URI-cached packaging, runtime_env/packaging.py)."""
        if not runtime_env:
            runtime_env = self.default_runtime_env
            if not runtime_env:
                return {}, ""
        import json as _json

        from ray_tpu import runtime_env as renv_mod

        key = _json.dumps(runtime_env, sort_keys=True, default=str)
        cached = self._renv_cache.get(key)
        if cached is None:
            norm = renv_mod.package(runtime_env, self.kv_put, self.kv_get)
            cached = (norm, renv_mod.env_hash(norm))
            self._renv_cache[key] = cached
        return cached

    def submit_task(
        self,
        func_blob: bytes,
        args: tuple,
        kwargs: dict,
        *,
        func_sha: str | None = None,
        num_returns: int = 1,
        resources: dict | None = None,
        max_retries: int = 0,
        name: str = "",
        strategy: dict | None = None,
        runtime_env: dict | None = None,
    ) -> list[ObjectRef]:
        task_id = TaskID().hex()
        spec_part, deps = self._serialize_args(args, kwargs)
        renv, rhash = self._prepare_runtime_env(runtime_env)
        # refs nested in args may be this process's unpublished direct-task
        # results: the GCS (and any borrower) must be able to resolve them
        self._publish_owned(spec_part.get("ref_holds", ()))
        # submitter's refs must be counted at the GCS before the task can
        # possibly complete: otherwise a borrower's death could free an
        # object whose only counted ref was the borrower's (the submitter's
        # +1 still in its 0.2s flush window)
        self._flush_ref_deltas()
        fn_field: dict
        if func_sha is not None:
            # content-addressed function store (reference: the GCS function
            # table with export-once semantics, function_manager.py): the
            # blob uploads once per cluster; every spec carries 20 bytes
            now = time.monotonic()
            # re-probe periodically even when memoized: the GCS function
            # store evicts past its budget, and a permanently-memoized sha
            # would then fail every future task using it
            if now - self._shipped_fns.get(func_sha, -1e9) > 60.0:
                key = "fn:" + func_sha
                # metadata-only existence probe — kv_get would pull the
                # whole blob just to discard it
                if not self.kv_keys(key):
                    self.kv_put(key, func_blob)
                self._shipped_fns[func_sha] = now
            fn_field = {"func_sha": func_sha}
        else:
            fn_field = {"func": func_blob}
        spec = {
            "kind": "task",
            "task_id": task_id,
            **fn_field,
            "deps": deps,
            "num_returns": num_returns,
            "resources": resources or {"CPU": 1.0},
            "max_retries": max_retries,
            "retries_used": 0,
            "name": name,
            "strategy": strategy,
            "caller_ns": self.effective_namespace(),
            **({"runtime_env": renv, "renv_hash": rhash} if rhash else {}),
            **_trace_field(),
            **spec_part,
        }
        # typed-spec validation at the submission boundary (reference:
        # TaskSpecification — malformed options fail HERE, at the caller)
        from ray_tpu._private.task_spec import validate_task

        validate_task(spec)
        if (self._direct_enabled and strategy is None
                and isinstance(num_returns, int)
                and self._try_submit_direct(spec)):
            return [ObjectRef(f"{task_id}r{i:04d}") for i in range(num_returns)]
        self._prepare_gcs_deps(deps)
        # fire-and-forget (reference: .remote() never blocks on the control
        # plane); every Nth submit is synchronous so a flood of submissions
        # stays bounded by what the GCS has actually admitted
        self._submit_seq += 1
        if self._submit_seq % 512 == 0:
            self.rpc({"type": "submit_task", "spec": spec})
        else:
            self.send_no_reply({"type": "submit_task", "spec": spec})
        if num_returns == "streaming":
            return ObjectRefGenerator(task_id, self)
        return [ObjectRef(f"{task_id}r{i:04d}") for i in range(num_returns)]

    def submit_cross_lang_task(self, func_name: str, args: list, *,
                               lang: str, resources: dict | None = None):
        """Submit a task for a cross-language worker: args/results are
        JSON values, functions are referenced by NAME (reference: the
        C++/Java worker APIs call registered functions cross-language)."""
        from ray_tpu._private.ids import TaskID

        task_id = TaskID().hex()
        spec = {
            "kind": "task",
            "task_id": task_id,
            "lang": lang,
            "func_name": func_name,
            "args": args,
            "deps": [],
            "num_returns": 1,
            "resources": resources or {"CPU": 1.0},
            "max_retries": 0,
            "retries_used": 0,
            "name": f"{lang}:{func_name}",
            "strategy": None,
        }
        # always the GCS path: leases/direct push are Python-worker planes
        self.rpc({"type": "submit_task", "spec": spec})
        return ObjectRef(f"{task_id}r0000")

    # -------------------------------------------------------- direct path
    # Lease-based caller→worker submission (reference: leased-worker task
    # pushes, normal_task_submitter.h:81; locality via lease_policy.h).

    def _dispatcher(self):
        if self._direct is None:
            from ray_tpu._private.direct import DirectDispatcher

            self._direct = DirectDispatcher(self)
        return self._direct

    def _classify_deps(self, deps):
        """Decide direct-eligibility from dependency state. Returns None
        (→ GCS path) or (inline_deps, required_lease, prefer_host)."""
        inline_deps: dict[str, bytes] = {}
        required_lease = None
        prefer_host = None
        best = -1
        disp = self._direct
        promised: list[str] = []  # sent after _owned_lock is released
        try:
            for d in deps:
                with self._owned_lock:
                    ent = self._owned.get(d)
                    if ent is not None:
                        st = ent.get("status")
                        if st == "pending":
                            # chain: runnable only on the dep's own lease (the
                            # worker computes the dep first, in order)
                            lease = disp.by_wid.get(ent.get("lease") or "") if disp else None
                            if lease is None or lease.dead or (
                                    required_lease is not None
                                    and lease is not required_lease):
                                return None
                            required_lease = lease
                            if not ent.get("publish_on_done"):
                                # safety net: if anything else ends up waiting
                                # on this oid at the GCS, the publish will come
                                ent["publish_on_done"] = True
                                self.incref(d)
                                promised.append(d)
                            continue
                        if st == "redirect":
                            return None  # GCS owns this task now
                        if st == "error":
                            return None  # error propagation is the GCS path's job
                        if ent.get("where") == "inline":
                            if not ent.get("published"):
                                inline_deps[d] = ent["inline"]
                            continue
                        if ent.get("size", 0) > best:
                            best, prefer_host = ent["size"], ent.get("host")
                        continue
                if d in self._memory or d in self._plasma_refs:
                    continue  # materialized locally → ready cluster-wide
                lc = self._loc_cache.get(d)
                if lc is None:
                    return None  # unknown readiness → let the GCS queue it
                host, size = lc
                if host is not None and size > best:
                    best, prefer_host = size, host
            return inline_deps, required_lease, prefer_host
        finally:
            # let the GCS fail the stub if this process dies before
            # delivering the promised publish
            for d in promised:
                self.send_no_reply({"type": "will_publish",
                                    "oid": d, "wid": self.wid})

    def _prepare_gcs_deps(self, deps):
        """Before a GCS-path submit: make every dep resolvable there."""
        self._publish_owned(deps)

    def _publish_owned(self, oids):
        """Ensure this process's direct-task results are visible at the GCS
        (called whenever such a ref escapes this process)."""
        for oid in oids:
            msg = None
            with self._owned_lock:
                ent = self._owned.get(oid)
                if ent is None or ent.get("published"):
                    continue
                st = ent.get("status")
                if st == "pending":
                    if not ent.get("publish_on_done"):
                        ent["publish_on_done"] = True
                        self.incref(oid)
                        # let the GCS fail the stub if this process dies
                        # before delivering the promised publish (sent
                        # outside the lock, below)
                        msg = {"type": "will_publish", "oid": oid,
                               "wid": self.wid}
                elif st == "redirect":
                    continue
                else:
                    # flip to GCS-visible atomically with re-emitting the
                    # suppressed +1 (incref/decref consult _gcs_invisible
                    # under _ref_lock, so holding it here closes the race —
                    # same pattern as _redirect_to_gcs)
                    with self._ref_lock:
                        ent["published"] = True
                        if self._local_refs.get(oid, 0) > 0:
                            self._ref_deltas[oid] = self._ref_deltas.get(oid, 0) + 1
                    if ent.get("where") == "inline":
                        msg = {"type": "object_put", "oid": oid, "where": "inline",
                               "inline": ent["inline"], "size": ent.get("size", 0),
                               "is_error": st == "error",
                               "contained": ent.get("contained") or None}
            if msg is not None:
                self.send_no_reply(msg)

    def _try_submit_direct(self, spec: dict) -> bool:
        disp = self._dispatcher()
        cls = self._classify_deps(spec.get("deps", ()))
        if cls is None:
            return False
        inline_deps, required_lease, prefer_host = cls
        from ray_tpu._private.direct import shape_key

        key = shape_key(spec["resources"], spec.get("renv_hash", ""))
        if inline_deps:
            spec["inline_deps"] = inline_deps
        spec["_direct"] = True  # task events carry this so GCS counters see it
        tid = spec["task_id"]
        holds = list(spec.get("deps", ())) + list(spec.get("ref_holds", ()))
        for d in holds:
            self.incref(d)
        self._flight_holds[tid] = holds
        with self._owned_lock:
            for i in range(spec["num_returns"]):
                self._owned[f"{tid}r{i:04d}"] = {
                    "status": "pending", "fut": _Future(), "lease": None,
                    "task_id": tid, "published": False}
        spec.pop("strategy", None)
        if not disp.submit_or_queue(key, spec, spec["resources"],
                                    spec.get("renv_hash", ""), prefer_host,
                                    required_lease):
            # no pool for this shape: roll back, the GCS path runs it
            for d in self._flight_holds.pop(tid, ()):
                self.decref(d)
            with self._owned_lock:
                for i in range(spec["num_returns"]):
                    self._owned.pop(f"{tid}r{i:04d}", None)
            spec.pop("inline_deps", None)
            spec.pop("_direct", None)  # GCS path counts it; avoid doubling
            return False
        return True

    def _note_direct_lease(self, spec: dict, wid: str) -> None:
        """Record which lease a direct spec was pushed to (dep-chaining)."""
        tid = spec["task_id"]
        with self._owned_lock:
            for i in range(spec["num_returns"]):
                ent = self._owned.get(f"{tid}r{i:04d}")
                if ent is not None:
                    ent["lease"] = wid

    def _direct_cancelled_local(self, spec: dict) -> None:
        """A spec cancelled straight out of the caller's local queue."""
        for d in self._flight_holds.pop(spec["task_id"], ()):
            self.decref(d)
        publish_later: list[str] = []
        with self._owned_lock:
            self._owned_fail_locked(
                spec, TaskCancelledError("task was cancelled"), publish_later)
        self._publish_owned(publish_later)
        for oid in publish_later:
            self.decref(oid)

    def _redirect_to_gcs(self, spec: dict) -> None:
        """Hand a direct spec over to the GCS path (lease pool collapsed or
        worker-death retry): its return objects become GCS-owned."""
        tid = spec["task_id"]
        publish_later: list[str] = []
        # deps whose blobs ride in inline_deps were never published; the GCS
        # gates dispatch on their readiness, so publish them now
        self._publish_owned(spec.get("deps", ()))
        with self._owned_lock:
            for i in range(spec["num_returns"]):
                oid = f"{tid}r{i:04d}"
                ent = self._owned.get(oid)
                if ent is None:
                    continue
                if ent.pop("publish_on_done", False):
                    self.decref(oid)
                # flip to GCS-visible atomically with re-emitting the
                # suppressed +1 (decref takes _ref_lock before consulting
                # _gcs_invisible, so holding it here closes the race)
                with self._ref_lock:
                    ent["status"] = "redirect"
                    if self._local_refs.get(oid, 0) > 0:
                        self._ref_deltas[oid] = self._ref_deltas.get(oid, 0) + 1
                ent["fut"].set({"ready": False, "redirect": True})
        spec["strategy"] = None
        spec.pop("_cancelled", None)
        spec.pop("_direct", None)  # the GCS path counts it from here on
        try:
            self.rpc({"type": "submit_task", "spec": spec})
        except Exception:
            with self._owned_lock:
                # entries are "redirect" now; recreate minimal error records
                for i in range(spec["num_returns"]):
                    oid = f"{tid}r{i:04d}"
                    if oid in self._owned:
                        self._owned.pop(oid)
            # the GCS is gone: getters will fail on their own RPCs
        for d in self._flight_holds.pop(tid, ()):
            self.decref(d)

    def _on_direct_done(self, lease, spec: dict, done: dict):
        tid = spec["task_id"]
        err = done.get("error")
        contained = done.get("contained") or {}
        published = set(done.get("published") or ())
        publish_later: list[str] = []
        with self._owned_lock:
            if done.get("cancelled"):
                self._owned_fail_locked(
                    spec, TaskCancelledError("task was cancelled"),
                    publish_later)
            else:
                for res in done.get("results") or ():
                    oid, where, inline, size = res[:4]
                    ent = self._owned.get(oid)
                    if ent is None:
                        continue  # every ref already dropped
                    was_published = oid in published
                    ent.update(
                        status="error" if err is not None else "ready",
                        where=where, inline=inline, size=size,
                        host=lease.host,
                        contained=list(contained.get(oid) or ()))
                    if was_published:
                        # worker registered it at the GCS (shm/contained):
                        # flip visibility and surface this process's
                        # suppressed refs atomically (see _redirect_to_gcs)
                        with self._ref_lock:
                            ent["published"] = True
                            if self._local_refs.get(oid, 0) > 0:
                                self._ref_deltas[oid] = \
                                    self._ref_deltas.get(oid, 0) + 1
                    else:
                        ent["published"] = False
                    if ent.pop("publish_on_done", False):
                        publish_later.append(oid)
                    ent["fut"].set({"ready": True})
        for d in self._flight_holds.pop(tid, ()):
            self.decref(d)
        self._publish_owned(publish_later)
        for oid in publish_later:
            self.decref(oid)  # the publish_on_done guard ref

    def _owned_fail_locked(self, spec: dict, exc, publish_later: list):
        """Mark a direct task's return objects errored (owned-side analogue
        of the GCS's _fail_task_objects). Caller holds _owned_lock."""
        blob = ser.dumps(exc)
        tid = spec["task_id"]
        for i in range(spec["num_returns"]):
            oid = f"{tid}r{i:04d}"
            ent = self._owned.get(oid)
            if ent is None:
                continue
            ent.update(status="error", where="inline", inline=blob,
                       size=len(blob), contained=[], published=False)
            if ent.pop("publish_on_done", False):
                publish_later.append(oid)
            ent["fut"].set({"ready": True})

    def _direct_task_failed(self, spec: dict, lease):
        """The leased worker died with this spec in flight."""
        tid = spec["task_id"]
        publish_later: list[str] = []
        if spec.pop("_cancelled", False):
            for d in self._flight_holds.pop(tid, ()):
                self.decref(d)
            with self._owned_lock:
                self._owned_fail_locked(
                    spec, TaskCancelledError("task was cancelled"),
                    publish_later)
        elif (spec.get("retries_used", 0) < spec.get("max_retries", 0)
              and self._alive):
            # hand the retry to the GCS: it owns queuing, spawn, and any
            # further retries (reference: task resubmission on worker death)
            spec["retries_used"] = spec.get("retries_used", 0) + 1
            self._redirect_to_gcs(spec)
            return
        else:
            for d in self._flight_holds.pop(tid, ()):
                self.decref(d)
            # the GCS may know more (e.g. the memory monitor killed it);
            # fetched lazily and cached per lease so N failed specs cost one
            # short RPC, and retry/cancel paths never pay it
            if lease.death_reason is None:
                try:
                    lease.death_reason = self.rpc(
                        {"type": "worker_death_reason", "wid": lease.wid},
                        timeout=2.0).get("reason") or ""
                except Exception:
                    lease.death_reason = ""
            why = lease.death_reason or f"worker {lease.wid} died"
            with self._owned_lock:
                self._owned_fail_locked(
                    spec, WorkerCrashedError(why), publish_later)
        self._publish_owned(publish_later)
        for oid in publish_later:
            self.decref(oid)

    def create_actor(
        self,
        cls_blob: bytes,
        args: tuple,
        kwargs: dict,
        *,
        resources: dict | None = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        name: str | None = None,
        namespace: str | None = None,
        strategy: dict | None = None,
        max_concurrency: int = 1,
        runtime_env: dict | None = None,
        concurrency_groups: dict | None = None,
        concurrency_group_methods: dict | None = None,
        class_name: str | None = None,
    ) -> str:
        actor_id = ActorID().hex()
        task_id = TaskID().hex()
        spec_part, deps = self._serialize_args(args, kwargs)
        renv, rhash = self._prepare_runtime_env(runtime_env)
        self._publish_owned(spec_part.get("ref_holds", ()))
        self._prepare_gcs_deps(deps)
        self._flush_ref_deltas()  # see submit_task: count refs before submit
        spec = {
            "kind": "actor_create",
            "task_id": task_id,
            "actor_id": actor_id,
            "func": cls_blob,
            "deps": deps,
            "num_returns": 0,
            "resources": resources or {"CPU": 1.0},
            "max_restarts": max_restarts,
            "max_task_retries": max_task_retries,
            "name": name,
            # human-readable class for state/timeline labels (the GCS only
            # ever sees the pickled blob otherwise)
            "class_name": class_name,
            "namespace": namespace or self.effective_namespace(),
            "strategy": strategy,
            # the GCS gates dispatch on total concurrency: named groups
            # add their limits on top of the default pool (reference:
            # concurrency groups have independent limits)
            "max_concurrency": max_concurrency + sum(
                (concurrency_groups or {}).values()),
            "concurrency_groups": concurrency_groups or {},
            # method → group map: lets the GCS dispatch group methods
            # through their own lane (see _dispatch_actor_grouped_locked)
            "concurrency_group_methods": concurrency_group_methods or {},
            **({"runtime_env": renv, "renv_hash": rhash} if rhash else {}),
            **_trace_field(),
            **spec_part,
        }
        from ray_tpu._private.task_spec import validate_actor

        validate_actor(spec)
        reply = self.rpc({"type": "create_actor", "spec": spec})
        if not reply.get("ok"):
            raise ValueError(reply.get("error") or "actor creation rejected")
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_task_retries: int | None = None,
    ) -> list[ObjectRef]:
        task_id = TaskID().hex()
        spec_part, deps = self._serialize_args(args, kwargs)
        self._publish_owned(spec_part.get("ref_holds", ()))
        self._prepare_gcs_deps(deps)
        self._flush_ref_deltas()  # see submit_task: count refs before submit
        spec = {
            "kind": "actor_task",
            "task_id": task_id,
            "actor_id": actor_id,
            "method": method_name,
            "deps": deps,
            "num_returns": num_returns,
            "resources": {},
            "caller_ns": self.effective_namespace(),
            **_trace_field(),
            **spec_part,
        }
        if max_task_retries is not None:
            # per-spec override of the actor's death-retry budget (the
            # compiled-DAG exec loop pins 0: a lost loop must fail, not be
            # replayed on the restarted actor — see gcs worker-death path)
            spec["max_task_retries"] = int(max_task_retries)
        if num_returns == "streaming":
            # stream state must exist before the generator polls: stay sync
            reply = self.rpc({"type": "actor_task", "spec": spec})
            if not reply.get("ok"):
                raise ActorDiedError(f"actor {actor_id[:8]} is dead")
            return ObjectRefGenerator(task_id, self)
        # async push: one-way send — a dead actor fails the result objects
        # and the error surfaces at get(), same as the reference
        self.send_no_reply({"type": "actor_task_async", "spec": spec})
        return [ObjectRef(f"{task_id}r{i:04d}") for i in range(num_returns)]

    def wait_actor_ready(self, actor_id: str, timeout: float | None = None):
        reply = self.rpc({"type": "wait_actor_ready", "aid": actor_id}, timeout=timeout or 120.0)
        if not reply.get("ok"):
            raise ActorDiedError(reply.get("error") or "actor failed to start")

    def kill_actor(self, actor_id: str, no_restart: bool = True):
        self.rpc({"type": "kill_actor", "aid": actor_id, "no_restart": no_restart})

    # ---------------------------------------------------------------- objects

    def put(self, value: Any, pin: bool = False) -> ObjectRef:
        """Store a value; `pin=True` exempts it from automatic GC (for
        infrastructure objects handed around by raw id, e.g. channels)."""
        oid = ObjectID.for_put().hex()
        (parts, total), contained = _serialize_capturing(ser.dumps_into, value)
        self._publish_owned(contained)  # nested direct-result refs escape
        if total <= INLINE_LIMIT:
            blob = b"".join(bytes(p) if not isinstance(p, bytes) else p for p in parts)
            self.send_no_reply({"type": "object_put", "oid": oid, "where": "inline",
                                "inline": blob, "size": total, "pin": pin,
                                "contained": contained})
        else:
            tier = self.store.put_parts(oid, parts, total)
            self.send_no_reply({"type": "object_put", "oid": oid, "where": "shm",
                                "size": total, "host": self.host_id, "pin": pin,
                                "contained": contained, "tier": tier})
        return ObjectRef(oid)

    def _ensure_local(self, oid: str, reply: dict) -> dict:
        """Guarantee `oid` is readable in this process (inline payload or a
        local store copy), pulling cross-host and triggering lineage
        reconstruction as needed. Returns the final wait_object reply.
        (reference: object_recovery_manager.h:41.)"""
        for _ in range(MAX_RECON_ATTEMPTS):
            if reply["where"] == "inline":
                return reply
            if self.store.contains(oid) or self._pull_remote(oid, reply):
                return reply
            # every advertised copy is gone (host died / store evicted): ask
            # the GCS to reconstruct from lineage, then wait again
            action = self.rpc({"type": "object_lost", "oid": oid})["action"]
            if action in ("reconstructing", "pending", "ready"):
                reply = self.rpc({"type": "wait_object", "oid": oid},
                                 timeout=600.0)
                continue
            raise ObjectLostError(
                f"object {oid[:12]}… lost: all copies gone and no lineage "
                f"to reconstruct it (action={action})")
        raise ObjectLostError(
            f"object {oid[:12]}… unrecoverable after "
            f"{MAX_RECON_ATTEMPTS} reconstruction attempts")

    def _materialize(self, oid: str, reply: dict) -> Any:
        reply = self._ensure_local(oid, reply)
        if reply["where"] == "inline":
            value = self._loads_restoring(reply["inline"])
        else:
            plasma = self.store.get(oid)
            self._plasma_refs[oid] = plasma
            value = self._loads_restoring(plasma.buf, owner=plasma)
        if reply["status"] == "error":
            raise value
        self._memory[oid] = value
        return value

    def _loads_restoring(self, buf, owner=None):
        """Deserialize, resolving RDT markers when (and only when) the
        payload constructed one during unpickling — exact detection at any
        nesting depth (reference: RDT materialization on get). `owner` is
        the store pin wrapper backing `buf`: zero-copy arrays tether it so
        the arena slot cannot be recycled while they are alive, even after
        the ref itself is freed."""
        from ray_tpu.experimental.device_objects import marker_capture, restore

        with marker_capture() as saw:
            value = ser.loads(buf, owner=owner)
        if saw():
            value = restore(value, self)
        return value

    def _pull_remote(self, oid: str, reply: dict) -> bool:
        """Object is in shm on another host: chunk-pull it into the local
        store and register the new copy (reference: pull-on-demand,
        object_manager.h:128). Returns False when no copy is reachable."""
        from ray_tpu._private.object_transfer import ObjectFetcher

        if self._fetcher is None:
            self._fetcher = ObjectFetcher(self.store)
        locations = reply.get("locations") or []
        for host, addr in locations:
            if host == self.host_id or not addr:
                continue
            tier = self._fetcher.fetch(oid, addr)
            if tier:
                if tier not in ("shm", "spill"):
                    # fetch dedup'd into a concurrent pull: ask the store
                    # which tier the winner actually landed on
                    tier = self.store.tier_of(oid) or "shm"
                self.send_no_reply({"type": "object_put", "oid": oid,
                                    "where": "shm", "size": reply.get("size", 0),
                                    "host": self.host_id, "tier": tier})
                return True
        return False

    def get_object(self, oid: str, timeout: float | None = None) -> Any:
        if oid in self._memory:
            return self._memory[oid]
        ent = self._owned.get(oid)
        if ent is not None and ent.get("status") != "redirect":
            # a direct-task result this process owns: no GCS round-trip
            if not ent["fut"].event.is_set() and self._direct is not None:
                self._direct.flush()  # it may still be in the local queue
            ent["fut"].wait(timeout if timeout is not None else 86400.0)
            with self._owned_lock:
                ent = self._owned.get(oid, ent)
                st = ent.get("status")
                where, inline = ent.get("where"), ent.get("inline")
            if st in ("ready", "error") and where == "inline":
                value = self._loads_restoring(inline)
                if st == "error":
                    raise value
                self._memory[oid] = value
                return value
            if st == "ready" and where == "shm":
                if self.store.contains(oid):
                    plasma = self.store.get(oid)
                    self._plasma_refs[oid] = plasma
                    value = self._loads_restoring(plasma.buf, owner=plasma)
                    self._memory[oid] = value
                    return value
                if (ent.get("host") or self.host_id) == self.host_id:
                    # the owned local copy vanished (deleted, or evicted
                    # without a spill). wait_object would park forever: an
                    # unpublished direct result has no GCS entry to wait on.
                    # Drive the pull/reconstruct loop instead — object_lost
                    # replays the retained lineage spec.
                    reply = {"ready": True, "status": st, "where": where,
                             "inline": None, "size": ent.get("size", 0),
                             "locations": []}
                    return self._materialize(oid, reply)
            # redirected to the GCS (retry) or a remote shm copy: fall through
        reply = self.rpc({"type": "wait_object", "oid": oid},
                         timeout=timeout if timeout is not None else 86400.0)
        self._note_locations(oid, reply)
        return self._materialize(oid, reply)

    def _note_locations(self, oid: str, reply: dict) -> None:
        """Cache readiness + primary host of a GCS-known object; direct
        submission uses this for locality-aware lease targeting."""
        if not reply.get("ready") or reply.get("status") == "pending":
            return
        host = None
        locs = reply.get("locations") or ()
        if locs:
            host = locs[0][0]
        self._loc_cache[oid] = (host, reply.get("size", 0))
        if reply.get("status") in ("ready", "error"):
            self._status_cache[oid] = reply["status"]
            if len(self._status_cache) > 4096:
                for k in list(self._status_cache)[:1024]:
                    self._status_cache.pop(k, None)
        if len(self._loc_cache) > 4096:
            for k in list(self._loc_cache)[:1024]:
                self._loc_cache.pop(k, None)

    def error_of(self, oid: str):
        """The exception a ready-but-errored object carries, or None.

        `wait()` reports errored objects as ready, so a completion poll
        that forwards "ready" refs downstream would forward poison; this
        probe answers error-ness WITHOUT fetching successful payloads
        (error blobs are always inline, and `_note_locations` caches the
        status of every ref wait() resolved, so the healthy path is
        RPC-free). Never raises — an unreachable GCS is inconclusive and
        returns None, leaving the error to surface at the eventual
        `get()`. Call only on refs `wait()` already reported ready: the
        fallback RPC blocks until the object resolves."""
        if oid in self._memory:
            return None  # only successful gets land in _memory
        ent = self._owned.get(oid)
        if ent is not None and ent.get("status") != "redirect":
            st = ent.get("status")
            if st == "ready":
                return None
            if st == "error":
                try:
                    return self._loads_restoring(ent.get("inline"))
                except Exception as exc:
                    return exc
        if self._status_cache.get(oid) == "ready":
            return None
        try:
            # short timeout: callers hold the contract that the ref is
            # already wait()-ready, so the GCS answers immediately — and
            # this runs inside the executor's pump loop, where a long
            # block per cache-missed ref would stall driver-side dispatch
            reply = self.rpc({"type": "wait_object", "oid": oid},
                             timeout=2.0)
        except Exception:
            return None
        self._note_locations(oid, reply)
        if reply.get("status") != "error":
            return None
        try:
            if reply.get("inline") is not None:
                return self._loads_restoring(reply["inline"])
            self._materialize(oid, reply)  # errored objects raise here
        except Exception as exc:
            return exc
        return WorkerCrashedError(
            f"object {oid[:12]}… errored but its payload is unavailable")

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            out.append(self.get_object(r.hex(), timeout=remaining))
        return out[0] if single else out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1, timeout: float | None = None):
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        if self._direct is not None:
            self._direct.flush()  # some refs may still sit in the local queue
        futures: list[tuple[ObjectRef, _Future | None]] = []
        for r in refs:
            oid = r.hex()
            if oid in self._memory:
                futures.append((r, None))
                continue
            ent = self._owned.get(oid)
            if ent is not None and ent.get("status") != "redirect":
                futures.append((r, ent["fut"]))
                continue
            # one outstanding GCS waiter per object, however often wait() polls
            fut = self._obj_waits.get(oid)
            if fut is None:
                fut = self.rpc_async({"type": "wait_object", "oid": oid})
                self._obj_waits[oid] = fut
            futures.append((r, fut))
        deadline = None if timeout is None else time.monotonic() + timeout

        def is_ready(f: _Future | None) -> bool:
            # a "connection lost" error reply is NOT object-ready
            return f is None or (f.event.is_set() and bool(f.value.get("ready")))

        while True:
            # an owned fut can resolve to a redirect (direct task handed to
            # the GCS on retry): swap in a GCS waiter for it
            for idx, (r, f) in enumerate(futures):
                if (f is not None and f.event.is_set()
                        and isinstance(f.value, dict)
                        and f.value.get("redirect")):
                    oid = r.hex()
                    nf = self._obj_waits.get(oid)
                    if nf is None:
                        nf = self.rpc_async({"type": "wait_object", "oid": oid})
                        self._obj_waits[oid] = nf
                    futures[idx] = (r, nf)
            ready = [r for r, f in futures if is_ready(f)]
            if len(ready) >= num_returns or (deadline is not None and time.monotonic() >= deadline):
                break
            if not self._alive:
                break
            time.sleep(0.002)
        ready_set = set()
        for r, f in futures:
            if is_ready(f) and len(ready_set) < num_returns:
                ready_set.add(r.hex())
        ready = [r for r in refs if r.hex() in ready_set]
        not_ready = [r for r in refs if r.hex() not in ready_set]
        for r in ready:
            fut = self._obj_waits.pop(r.hex(), None)
            if fut is not None and fut.event.is_set():
                self._note_locations(r.hex(), fut.value)
        return ready, not_ready

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> bool:
        """Cancel the task producing `ref` (reference: ray.cancel —
        CoreWorker::CancelTask). Queued tasks are dequeued; running ones are
        interrupted only with force=True (worker SIGKILL + normal
        death/retry bookkeeping, with retries suppressed)."""
        tid = ref.hex()[:-5]  # strip the rNNNN return suffix
        if self._direct is not None:
            r = self._direct.cancel(tid, force)
            if r is not None:
                return r
        reply = self.rpc({"type": "cancel_task", "task_id": tid,
                          "force": force})
        return bool(reply.get("cancelled"))

    def free(self, refs: Sequence[ObjectRef]):
        oids = [r.hex() for r in refs]
        for oid in oids:
            self._memory.pop(oid, None)
            self._plasma_refs.pop(oid, None)
            self._obj_waits.pop(oid, None)
            self._status_cache.pop(oid, None)
            with self._owned_lock:
                self._owned.pop(oid, None)
            self.store.delete(oid)
        self.rpc({"type": "free_objects", "oids": oids})

    # ------------------------------------------------------------------- kv

    def kv_put(self, key: str, value: bytes):
        self.rpc({"type": "kv_put", "key": key, "value": value})

    def kv_get(self, key: str) -> bytes | None:
        return self.rpc({"type": "kv_get", "key": key})["value"]

    def kv_keys(self, prefix: str = "") -> list[str]:
        return self.rpc({"type": "kv_keys", "prefix": prefix})["keys"]

    def kv_del(self, key: str):
        self.rpc({"type": "kv_del", "key": key})

    def effective_namespace(self) -> str:
        """The submitter's namespace inside a task, the driver's outside."""
        return getattr(self._task_ctx, "namespace", None) or self.namespace

    def get_named_actor(self, name: str,
                        namespace: str | None = None) -> str | None:
        reply = self.rpc({"type": "get_named_actor", "name": name,
                          "namespace": namespace or self.effective_namespace()})
        if reply.get("state") == "dead":
            # a dead actor's name is a tombstone (the GCS lets a new actor
            # claim it): callers must see "no such actor", not a handle
            # every call on which fails — e.g. serve._get_controller after
            # a shutdown must CREATE, and restarting actors still resolve
            return None
        return reply["aid"]

    # ------------------------------------------------------- placement groups

    def create_pg(self, pg_id: str, bundles: list[dict], strategy: str, name: str = ""):
        reply = self.rpc({"type": "create_pg", "spec": {
            "pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name}})
        if not reply.get("ok"):
            from ray_tpu.exceptions import PlacementGroupUnschedulableError

            raise PlacementGroupUnschedulableError(reply.get("error") or "pg rejected")

    def remove_pg(self, pg_id: str):
        self.rpc({"type": "remove_pg", "pg_id": pg_id})

    def pg_wait(self, pg_id: str, timeout: float | None = None) -> bool:
        try:
            reply = self.rpc({"type": "pg_wait", "pg_id": pg_id},
                             timeout=timeout if timeout is not None else 86400.0)
        except GetTimeoutError:
            return False
        return bool(reply.get("ok"))

    def pg_table(self) -> dict:
        return self.rpc({"type": "pg_table"})["table"]

    def get_named_pg(self, name: str) -> str | None:
        return self.rpc({"type": "get_named_pg", "name": name})["pg_id"]

    def add_node(self, node_id: str, resources: dict, labels: dict | None = None):
        self.rpc({"type": "add_node", "node_id": node_id, "resources": resources,
                  "labels": labels or {}})

    def remove_node(self, node_id: str):
        self.rpc({"type": "remove_node", "node_id": node_id})

    def list_nodes(self) -> list[dict]:
        return self.rpc({"type": "list_nodes"})["nodes"]

    def cluster_state(self) -> dict:
        return self.rpc({"type": "cluster_state"})["state"]

    # -------------------------------------------------------------- execution

    def _resolve_args(self, spec: dict) -> tuple[tuple, dict]:
        if "args_oid" in spec:
            oid = spec["args_oid"]
            if not self.store.contains(oid):
                # oversized args submitted from another host: pull (with the
                # same lost-object recovery as normal gets)
                reply = self.rpc({"type": "wait_object", "oid": oid}, timeout=300.0)
                self._ensure_local(oid, reply)
            plasma = self.store.get(oid)
            args, kwargs = self._loads_restoring(plasma.buf)
        else:
            args, kwargs = self._loads_restoring(spec["args"])
        inline_deps = spec.get("inline_deps") or {}

        def resolve(oid: str):
            if oid in self._memory:
                return self._memory[oid]
            # direct-path blobs: the caller attached its unpublished results
            blob = inline_deps.get(oid)
            if blob is not None:
                value = self._loads_restoring(blob)
                self._memory[oid] = value
                return value
            # chained direct task: the predecessor ran in THIS process
            ds = self.direct_server
            if ds is not None:
                rec = ds.recent.get(oid)
                if rec is not None:
                    where, inline, is_err = rec
                    if where == "inline" and inline is not None:
                        value = self._loads_restoring(inline)
                        if is_err:
                            raise value
                        self._memory[oid] = value
                        return value
                    if self.store.contains(oid):
                        plasma = self.store.get(oid)
                        self._plasma_refs[oid] = plasma
                        value = self._loads_restoring(plasma.buf)
                        if is_err:
                            raise value
                        self._memory[oid] = value
                        return value
            return self.get_object(oid)

        args = tuple(resolve(a.hex) if isinstance(a, _RefMarker) else a for a in args)
        kwargs = {k: resolve(v.hex) if isinstance(v, _RefMarker) else v for k, v in kwargs.items()}
        return args, kwargs

    @property
    def current_task_id(self) -> str | None:
        return getattr(self._task_ctx, "task_id", None)

    def _stream_results(self, spec: dict, out) -> None:
        """Drive a streaming task: each yielded value becomes its own object,
        reported incrementally; the producer pauses when it runs more than
        `backpressure` items ahead of the consumer (reference:
        _raylet.pyx:299 streaming generators with backpressure)."""
        task_id = spec["task_id"]
        bp = int(spec.get("backpressure") or 16)
        from ray_tpu._private.ray_config import RayConfig

        stall_budget = RayConfig.instance().stream_stall_timeout_s
        produced = 0
        stalled = False
        try:
            for val in out:
                if task_id in self._stream_cancelled:
                    break  # consumer dropped the generator
                oid = f"{task_id}s{produced:06d}"
                (parts, total), refs = _serialize_capturing(ser.dumps_into, val)
                msg = {"type": "stream_item", "wid": self.wid, "task_id": task_id,
                       "oid": oid, "size": total, "contained": refs}
                if total <= INLINE_LIMIT:
                    blob = b"".join(bytes(p) if not isinstance(p, bytes) else p
                                    for p in parts)
                    msg.update(where="inline", inline=blob)
                else:
                    tier = self.store.put_parts(oid, parts, total)
                    msg.update(where="shm", host=self.host_id, tier=tier)
                self.send_no_reply(msg)
                produced += 1
                stalled = False
                stall_t = 0.0
                while True:
                    if (task_id in self._stream_cancelled
                            or produced - self._stream_acks.get(task_id, 0) <= bp):
                        break
                    ev = self._stream_events.setdefault(task_id, threading.Event())
                    ev.clear()
                    if produced - self._stream_acks.get(task_id, 0) <= bp:
                        break  # ack raced the clear
                    # wait in short slices: any ack progress resets the stall
                    # clock, so only a consumer with NO progress for the whole
                    # budget fails the stream (budget 0 = wait forever while
                    # the GCS connection lives — reference blocks indefinitely)
                    if ev.wait(5.0):
                        stall_t = 0.0
                        continue
                    stall_t += 5.0
                    if stall_budget and stall_t >= stall_budget:
                        stalled = True  # consumer gone/stalled: stop, don't
                        break           # produce unboundedly past it
                if stalled:
                    break
            if stalled:
                # a merely-slow consumer must see an ERROR, not a clean
                # StopIteration with silently truncated results
                err = ser.dumps(RayTaskError(
                    spec.get("name") or "stream", "",
                    TimeoutError(
                        f"streaming producer stalled: consumer took no item "
                        f"for {stall_budget:.0f}s with the producer {bp} items "
                        f"ahead (produced {produced})")))
                self.send_no_reply({"type": "stream_end", "wid": self.wid,
                                    "task_id": task_id, "error": err})
            else:
                self.send_no_reply({"type": "stream_end", "wid": self.wid,
                                    "task_id": task_id, "error": None})
        finally:
            self._stream_acks.pop(task_id, None)
            self._stream_events.pop(task_id, None)
            self._stream_cancelled.discard(task_id)

    def execute_spec(self, spec: dict) -> dict:
        """Run a task spec to completion and return the task_done-shaped
        report (results, error, contained, device_tensors) WITHOUT sending
        it anywhere — the GCS exec path and the direct-dispatch path differ
        only in where the report goes."""
        kind = spec["kind"]
        error_blob = None
        results = []
        contained_map: dict = {}
        _extract_dev = False
        _dev_map: dict = {}  # oid → tensor ids contained in THAT result
        self._task_ctx.task_id = spec["task_id"]
        self._task_ctx.namespace = spec.get("caller_ns")
        strat = spec.get("strategy") or {}
        self._task_ctx.pg_id = (strat.get("pg_id")
                                if strat.get("kind") == "pg" else None)
        _t_exec0 = time.time()
        # trace propagation: the spec's injected context becomes the parent
        # of this task's span, and the span is current while user code runs
        # so nested .remote() calls chain under it (reference:
        # tracing_helper.py:165 _DictPropagator extract-before-execute)
        from ray_tpu.util import tracing as _tracing

        _tspan = _tracing.begin_task_span(spec.get("trace_ctx"))
        try:
            args, kwargs = self._resolve_args(spec)
            if kind == "task":
                key = spec.get("func_sha") or spec["func"]
                func = self._func_cache.get(key)
                if func is None:
                    blob = spec.get("func")
                    if blob is None:
                        blob = self.kv_get("fn:" + spec["func_sha"])
                        if blob is None:
                            raise RayTpuError(
                                f"function {spec['func_sha']} missing from "
                                "the cluster function store")
                    func = ser.loads(blob)
                    if len(self._func_cache) > 256:
                        self._func_cache.clear()
                    self._func_cache[key] = func
                out = func(*args, **kwargs)
            elif kind == "actor_create":
                cls = ser.loads(spec["func"])
                instance = cls(*args, **kwargs)
                self.actors[spec["actor_id"]] = instance
                self.current_actor_id = spec["actor_id"]
                from ray_tpu._private.actor_executor import ActorExecutor

                # concurrency groups + threaded/async execution
                # (reference: concurrency_group_manager.h, fiber.h async
                # actors, actor_scheduling_queue.h)
                self._actor_pools[spec["actor_id"]] = ActorExecutor(
                    instance,
                    max_concurrency=int(spec.get("max_concurrency") or 1),
                    concurrency_groups=spec.get("concurrency_groups") or {})
                out = None
            elif kind == "actor_task":
                instance = self.actors[spec["actor_id"]]
                if spec["method"] == EXEC_LOOP_METHOD:
                    # compiled-DAG channel plane: the provisioned per-actor
                    # loop runs as a (long-lived) actor task so teardown
                    # joins it through the normal result path (reference:
                    # compiled_dag_node.py do_exec_tasks). The executor is
                    # passed so async ops run on the actor's own event loop.
                    from ray_tpu.dag.channel_execution import actor_exec_loop

                    out = actor_exec_loop(
                        instance, *args,
                        _execer=self._actor_pools.get(spec["actor_id"]),
                        **kwargs)
                else:
                    method = getattr(instance, spec["method"])
                    import inspect as _inspect

                    if _inspect.iscoroutinefunction(
                            getattr(method, "__func__", method)):
                        # async method reached execute_task directly (pool
                        # routing already ran it on the loop when enabled)
                        execer = self._actor_pools.get(spec["actor_id"])
                        out = execer.run_coroutine_sync(method(*args, **kwargs))
                    else:
                        out = method(*args, **kwargs)
                    if getattr(getattr(method, "__func__", method),
                               TENSOR_TRANSPORT_ATTR, None):
                        _extract_dev = True
            else:
                raise RayTpuError(f"unknown task kind {kind}")
            n = spec["num_returns"]
            if n == "streaming":
                self._stream_results(spec, out)
                values = []
                n = 0
            else:
                values = [out] if n == 1 else (list(out) if n > 0 else [])
            if isinstance(n, int) and n > 1 and len(values) != n:
                raise ValueError(f"task declared num_returns={n} but returned {len(values)} values")
            if _extract_dev:
                # RDT: returned jax.Arrays stay in this process's HBM; only
                # small markers cross the control plane. Extraction is PER
                # RETURN VALUE so the GCS can free each result's registry
                # entries independently (freeing return 0 must not drop
                # tensors still referenced by a live return 1).
                from ray_tpu.experimental import device_objects

                for i in range(len(values)):
                    values[i], tids = device_objects.extract(values[i], self.wid)
                    if tids:
                        _dev_map[f"{spec['task_id']}r{i:04d}"] = tids
            for i, val in enumerate(values):
                oid = f"{spec['task_id']}r{i:04d}"
                (parts, total), refs = _serialize_capturing(ser.dumps_into, val)
                if refs:
                    contained_map[oid] = refs
                if total <= INLINE_LIMIT:
                    blob = b"".join(bytes(p) if not isinstance(p, bytes) else p for p in parts)
                    results.append((oid, "inline", blob, total))
                else:
                    tier = self.store.put_parts(oid, parts, total)
                    results.append((oid, "shm", None, total, tier))
        except Exception as e:  # noqa: BLE001 — task errors must be captured, not crash the worker
            tb = traceback.format_exc()
            wrapped = RayTaskError(spec.get("name") or spec.get("method", kind), tb, e)
            try:
                blob = ser.dumps(wrapped)
            except Exception:
                # the cause (or a return value) wasn't picklable; keep the traceback
                wrapped = RayTaskError(spec.get("name") or spec.get("method", kind), tb, None)
                blob = ser.dumps(wrapped)
            error_blob = repr(e)
            if spec["num_returns"] == "streaming":
                # mid-stream failure: already-yielded items stay readable,
                # the consumer's next() raises the error
                self.send_no_reply({"type": "stream_end", "wid": self.wid,
                                    "task_id": spec["task_id"], "error": blob})
                results = []
            else:
                results = [
                    (f"{spec['task_id']}r{i:04d}", "inline", blob, len(blob))
                    for i in range(spec["num_returns"])
                ]
        finally:
            self._task_ctx.task_id = None
            self._task_ctx.namespace = None
            self._task_ctx.pg_id = None
            _tracing.end_task_span(
                _tspan, name=spec.get("name") or spec.get("method") or kind,
                task_id=spec["task_id"], kind=kind, ok=error_blob is None)
            # drop arg-value caches this task materialized unless user code
            # in this process also holds refs to them
            for dep in spec.get("deps", ()):
                with self._ref_lock:
                    held = self._local_refs.get(dep, 0) > 0
                if not held:
                    self._memory.pop(dep, None)
                    self._plasma_refs.pop(dep, None)
        from ray_tpu._private import task_events as _te

        _te.emit("task:execute", task_id=spec["task_id"],
                 name=spec.get("name") or spec.get("method") or kind,
                 start=_t_exec0, end=time.time(), kind=kind,
                 ok=error_blob is None, direct=spec.get("_direct", False),
                 **({"error": error_blob} if error_blob else {}))
        lite = {k: spec.get(k) for k in ("task_id", "kind", "actor_id", "resources", "num_returns", "max_retries", "retries_used")}
        # flush ref deltas BEFORE task_done on the same ordered connection:
        # refs this task deserialized/retained must reach the GCS before it
        # releases the task's system holds, or a borrowed ref could be freed
        # under us (reference: borrower protocol, reference_counter.h:43)
        self._flush_ref_deltas()
        done = {"type": "task_done", "wid": self.wid, "spec": lite,
                "task_id": spec["task_id"],
                "results": results, "error": error_blob,
                "contained": contained_map}
        if _dev_map:
            # registry lifetime rides each result object: the GCS tells us to
            # drop a result's HBM entries when THAT object is freed
            done["device_tensors"] = _dev_map
        return done

    def register_direct_results(self, spec: dict, done: dict, server) -> None:
        """After a direct task: make the outputs that need cluster-level
        bookkeeping visible at the GCS — shm results (locations, spilling,
        lineage for reconstruction) and inline results carrying nested refs
        (the GCS must hold those for future borrowers). Pure-inline results
        stay caller-local: zero GCS traffic on the hot path."""
        results = done.get("results") or ()
        contained = done.get("contained") or {}
        is_err = done.get("error") is not None
        published: list[str] = []
        any_shm = False
        for res in results:
            oid, where, inline, size = res[:4]
            server.note_recent(oid, where, inline, is_err)
            tier = res[4] if len(res) > 4 else "shm"
            if where == "shm":
                any_shm = True
                self.send_no_reply({
                    "type": "object_put", "oid": oid, "where": "shm",
                    "size": size, "host": self.host_id, "tier": tier,
                    "is_error": is_err,
                    "contained": contained.get(oid) or None})
                published.append(oid)
            elif contained.get(oid):
                self.send_no_reply({
                    "type": "object_put", "oid": oid, "where": "inline",
                    "inline": inline, "size": size, "is_error": is_err,
                    "contained": contained.get(oid)})
                published.append(oid)
        if (any_shm and spec.get("kind") == "task"
                and isinstance(spec.get("num_returns"), int)):
            # shm outputs are evictable/losable: retain lineage so the GCS
            # can reconstruct them (inline results die with their owner)
            lin = {k: v for k, v in spec.items() if k != "_cancelled"}
            self.send_no_reply({"type": "direct_lineage", "spec": lin})
        if published:
            done["published"] = published

    def execute_task(self, spec: dict) -> None:
        done = self.execute_spec(spec)
        self.send_no_reply(done)

    def exec_loop(self):
        """Main loop of worker processes (driver never calls this)."""
        while True:
            spec = self.exec_queue.get()
            if spec is None:
                return
            if (spec["kind"] == "actor_task"
                    and spec.get("method") == EXEC_LOOP_METHOD):
                # compiled-DAG exec loop: blocks until teardown, so it gets
                # a dedicated thread — other actors hosted by this process
                # must stay schedulable behind it. Actor serialization is
                # NOT weakened: the GCS dispatches ≤ max_concurrency tasks
                # per actor, and the loop occupies a slot for its lifetime,
                # so a plain actor's normal calls queue until teardown
                # rather than racing the loop.
                threading.Thread(target=self.execute_task, args=(spec,),
                                 daemon=True, name="dag-channel-loop").start()
                continue
            execer = (self._actor_pools.get(spec.get("actor_id"))
                      if spec["kind"] == "actor_task" else None)
            if execer is not None:
                execer.submit(spec, self.execute_task)
            else:
                self.execute_task(spec)

    def disconnect(self):
        global _ref_tracker
        if _ref_tracker is self:
            _ref_tracker = None
        self._disconnecting = True
        self._alive = False
        if self._direct is not None:
            try:
                self._direct.shutdown()
            except Exception:
                pass
        if self.direct_server is not None:
            try:
                self.direct_server.stop()
            except Exception:
                pass
        try:
            self._flush_ref_deltas()
        except Exception:
            pass
        if hasattr(self.store, "release_pid_pins") and self.kind != "driver":
            # clean-exit pin release: views this process still holds must
            # not keep blocking arena eviction after it is gone. Driver
            # processes are excluded: the pid-keyed sweep would also revoke
            # pins held by the in-process object server / GCS head store
            # (same pid, other ArenaStore instances), which may still be
            # serving a chunked send during shutdown.
            try:
                self.store.release_pid_pins()
            except Exception:
                pass
        try:
            self.conn.close()
        except Exception:
            pass


_global_worker: CoreWorker | None = None

# Process-wide drain state, set by the GCS `drain_notice` push when this
# worker's node enters DRAINING (preemption notice, autoscaler scale-down,
# `ray_tpu drain`). Train sessions poll drain_info() at step boundaries to
# trigger the preemption-grace checkpoint.
_drain_event = threading.Event()
_drain_info: dict | None = None


def _set_drain(msg: dict) -> None:
    global _drain_info
    if _drain_info is None:
        _drain_info = {"node_id": msg.get("node_id"),
                       "reason": msg.get("reason"),
                       "grace_s": msg.get("grace_s"),
                       "ts": time.time()}
    _drain_event.set()


def _reset_drain() -> None:
    """Forget the previous session's drain notice (called from
    shutdown()): the notice names a node of a cluster that no longer
    exists, and a fresh init() in the same process would otherwise see a
    phantom preemption on its first train step."""
    global _drain_info
    _drain_info = None
    _drain_event.clear()


def drain_info() -> dict | None:
    """The drain notice this process received, or None. Sticky for the
    session lifetime: a draining node never un-drains while its cluster
    is alive."""
    return _drain_info


def drain_requested() -> bool:
    return _drain_event.is_set()


def get_global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RayTpuError("ray_tpu.init() has not been called in this process")
    return _global_worker


def set_global_worker(w: CoreWorker | None):
    global _global_worker
    _global_worker = w
