"""Worker bootstrap shim: build the pip runtime env, then exec worker_main.

Spawned instead of worker_main when the runtime env carries a "pip" field:
the (possibly slow) venv creation happens HERE, in the worker process, so
the scheduler thread never blocks on pip; the process then re-execs under
the venv interpreter with ray_tpu's location pinned on PYTHONPATH.

(reference: the runtime-env agent materializes envs before worker start,
_private/runtime_env/agent/runtime_env_agent.py:165.)
"""

from __future__ import annotations

import json
import os
import sys


def _reexec_under(python: str) -> None:
    # ray_tpu itself isn't installed into the env: pin its parent dir
    pkg_parent = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    pp = os.environ.get("PYTHONPATH", "")
    parts = [p for p in pp.split(os.pathsep) if p]
    if pkg_parent not in parts:
        parts.insert(0, pkg_parent)
    os.environ["PYTHONPATH"] = os.pathsep.join(parts)
    os.execv(python, [python, "-m", "ray_tpu._private.worker_main"])


def main():
    renv = json.loads(os.environ.get("RAY_TPU_RUNTIME_ENV") or "{}")
    conda_spec = renv.get("conda")
    pip_spec = renv.get("pip")
    if conda_spec and pip_spec:
        raise SystemExit(
            "runtime_env cannot combine 'conda' and 'pip' — put pip "
            "packages under the conda spec's dependencies instead")
    agent_sock = os.environ.get("RAY_TPU_RENV_AGENT_SOCK")
    if agent_sock and (conda_spec or pip_spec):
        # per-host runtime-env agent: concurrent workers needing the same
        # env share ONE build and a broken env fails fast with the agent's
        # error; fall back to the local build path if the agent is gone
        reply = None
        try:
            from ray_tpu._private import runtime_env_agent
            from ray_tpu._private.protocol import ConnectionClosed

            reply = runtime_env_agent.get_or_create(agent_sock, renv)
        except (OSError, ConnectionError, ConnectionClosed):
            # agent unreachable: local fallback below. An agent-REPORTED
            # build failure (RuntimeError) propagates — retrying the same
            # broken build locally would just boot-loop the worker.
            pass
        # _reexec_under runs OUTSIDE the try: a KeyError/OSError raised
        # from inside the exec path must surface, not be misread as
        # "agent unreachable" and silently fall through to a second
        # build under the wrong interpreter assumption
        if reply is not None and reply.get("python"):
            _reexec_under(reply["python"])
    if conda_spec:
        from ray_tpu._private.runtime_env_conda import ensure_conda_env

        _reexec_under(ensure_conda_env(conda_spec))
    if pip_spec:
        from ray_tpu._private.runtime_env_pip import ensure_venv

        python = ensure_venv(pip_spec)
        _reexec_under(python)
    from ray_tpu._private import worker_main

    sys.exit(worker_main.main())


if __name__ == "__main__":
    main()
