"""Entry point for worker subprocesses.

(reference: the worker main loop in python/ray/_private/workers/default_worker.py
+ the execute-task callback _raylet.pyx:1823.)
"""

from __future__ import annotations

import os
import sys


def main():
    # head-host workers get the session unix socket; follower-host workers
    # (spawned by a node agent) get the GCS TCP address instead
    address = os.environ.get("RAY_TPU_ADDRESS") or f"unix:{os.environ['RAY_TPU_SOCKET']}"
    session_id = os.environ["RAY_TPU_SESSION"]
    from ray_tpu._private.worker import CoreWorker, set_global_worker

    worker = CoreWorker(address, session_id, kind="worker")
    set_global_worker(worker)
    renv_json = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if renv_json:
        # materialize working_dir / py_modules from the GCS package cache
        # before any task runs (reference: worker start through the
        # runtime-env agent, runtime_env_agent.py:303)
        import json

        from ray_tpu import runtime_env as _renv

        _renv.apply_to_process(json.loads(renv_json), worker.kv_get)
    code = 0
    try:
        worker.exec_loop()
    except BaseException:
        import traceback

        traceback.print_exc()  # worker log captures stderr
        code = 1
    finally:
        worker.disconnect()
        # hard exit: concurrent-actor pool threads are non-daemon and may be
        # mid-task (or blocked on a dead GCS); threading._shutdown would join
        # them forever and leak this process past driver death
        os._exit(code)


if __name__ == "__main__":
    sys.exit(main())
