"""ActorClass / ActorHandle / ActorMethod.

(reference: python/ray/actor.py — ActorClass:1188, ActorHandle:1857. Actor
method calls are ordered per handle, matching the reference's sequential
actor submit queue, src/ray/core_worker/task_submission/sequential_actor_submit_queue.h.)
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private import serialization as ser
from ray_tpu._private.constants import (CONCURRENCY_GROUP_ATTR,
                                        TENSOR_TRANSPORT_ATTR)
from ray_tpu.remote_function import _build_resources


def method(*, concurrency_group: str | None = None,
           tensor_transport: str | None = None):
    """Per-method options on actor classes (reference: @ray.method,
    python/ray/actor.py). `concurrency_group` routes the method to a named
    pool declared via @remote(concurrency_groups={...});
    `tensor_transport="device"` keeps returned jax.Arrays in the owner's
    HBM and passes them by reference (reference: RDT
    @ray.method(tensor_transport=...), gpu_object_manager.py:84).
    Return arity is set per call with `.options(num_returns=N)`."""

    def decorate(fn):
        if concurrency_group is not None:
            setattr(fn, CONCURRENCY_GROUP_ATTR, concurrency_group)
        if tensor_transport is not None:
            if tensor_transport not in ("device", "tpu"):
                raise ValueError(
                    f"tensor_transport must be 'device' (alias 'tpu'), got "
                    f"{tensor_transport!r}")
            setattr(fn, TENSOR_TRANSPORT_ATTR, tensor_transport)
        return fn

    return decorate


class ActorMethod:
    def __init__(self, actor_id: str, method_name: str, num_returns: int = 1):
        self._actor_id = actor_id
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, *, num_returns=None, **_ignored) -> "ActorMethod":
        return ActorMethod(self._actor_id, self._method_name,
                           self._num_returns if num_returns is None else num_returns)

    def remote(self, *args, **kwargs):
        from ray_tpu._private.api import _get_worker

        refs = _get_worker().submit_actor_task(
            self._actor_id, self._method_name, args, kwargs,
            num_returns=self._num_returns,
        )
        if self._num_returns == "streaming":
            return refs  # an ObjectRefGenerator
        return refs[0] if self._num_returns == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: str):
        self._actor_id = actor_id

    @property
    def actor_id(self) -> str:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self._actor_id, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:8]}…)"

    def __reduce__(self):
        # Handles rebind to the receiving process's global worker; the GCS
        # routes calls by actor id regardless of which process submits.
        return (ActorHandle, (self._actor_id,))

    def __ray_ready__(self, timeout: float | None = None):
        from ray_tpu._private.api import _get_worker

        _get_worker().wait_actor_ready(self._actor_id, timeout=timeout)
        return True


_UNSET = object()


class ActorClass:
    def __init__(self, cls, *, num_cpus=None, num_tpus=None, resources=None,
                 max_restarts=0, max_task_retries=0, name=None,
                 namespace=None, lifetime=None,
                 scheduling_strategy=None,
                 max_concurrency=1, runtime_env=None, concurrency_groups=None):
        self._cls = cls
        self._opts = {"num_cpus": num_cpus, "num_tpus": num_tpus, "resources": resources}
        self._resources = _build_resources(num_cpus, num_tpus, resources)
        self._max_restarts = max_restarts
        self._max_task_retries = max_task_retries
        self._name = name
        self._namespace = namespace
        self._strategy = scheduling_strategy
        self._max_concurrency = max_concurrency
        self._runtime_env = runtime_env
        self._concurrency_groups = dict(concurrency_groups or {})
        self._blob: bytes | None = None
        self.__name__ = getattr(cls, "__name__", "Actor")

    def _get_blob(self):
        if self._blob is None:
            ref = ser.class_ref_or_none(self._cls)
            self._blob = ser.dumps(ref if ref is not None else self._cls)
        return self._blob

    def options(self, *, num_cpus=None, num_tpus=None, resources=None,
                max_restarts=None, max_task_retries=None, name=None,
                namespace=None, lifetime=None,
                scheduling_strategy=_UNSET, max_concurrency=None,
                runtime_env=_UNSET, concurrency_groups=None,
                **_ignored) -> "ActorClass":
        ac = ActorClass(
            self._cls,
            num_cpus=self._opts["num_cpus"] if num_cpus is None else num_cpus,
            num_tpus=self._opts["num_tpus"] if num_tpus is None else num_tpus,
            resources=self._opts["resources"] if resources is None else resources,
            max_restarts=self._max_restarts if max_restarts is None else max_restarts,
            max_task_retries=(self._max_task_retries if max_task_retries
                              is None else max_task_retries),
            name=name if name is not None else self._name,
            namespace=namespace if namespace is not None else self._namespace,
            lifetime=lifetime,
            scheduling_strategy=(self._strategy if scheduling_strategy is _UNSET
                                 else scheduling_strategy),
            max_concurrency=(self._max_concurrency if max_concurrency is None
                             else max_concurrency),
            runtime_env=(self._runtime_env if runtime_env is _UNSET
                         else runtime_env),
            concurrency_groups=(self._concurrency_groups
                                if concurrency_groups is None
                                else concurrency_groups),
        )
        ac._blob = self._blob
        return ac

    def _concurrency_group_methods(self) -> dict:
        """method name → declared concurrency group (@ray_tpu.method). The
        map ships in the create spec so the GCS can dispatch group methods
        through their own lane instead of the default FIFO — a control call
        (e.g. a serve health probe) must not wait behind a saturated data
        queue."""
        out = {}
        for klass in reversed(getattr(self._cls, "__mro__", (self._cls,))):
            for name, fn in vars(klass).items():
                group = getattr(fn, CONCURRENCY_GROUP_ATTR, None)
                if group is not None:
                    out[name] = group
        return out

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private.api import _get_worker
        from ray_tpu.util.scheduling_strategies import strategy_to_spec

        worker = _get_worker()
        actor_id = worker.create_actor(
            self._get_blob() if worker.kind != "local" else self._cls,
            args,
            kwargs,
            resources=self._resources,
            max_restarts=self._max_restarts,
            max_task_retries=self._max_task_retries,
            name=self._name,
            namespace=self._namespace,
            strategy=strategy_to_spec(self._strategy),
            max_concurrency=self._max_concurrency,
            runtime_env=self._runtime_env,
            concurrency_groups=self._concurrency_groups,
            concurrency_group_methods=self._concurrency_group_methods(),
            class_name=getattr(self._cls, "__name__", None),
        )
        return ActorHandle(actor_id)

    def __call__(self, *args, **kwargs):
        raise TypeError("Actor classes must be instantiated with .remote()")

    @property
    def cls(self):
        return self._cls
