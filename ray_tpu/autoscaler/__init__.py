from .autoscaler import Autoscaler, NodeType
from .gce_tpu import (FakeGceTpuApi, GceTpuApi, GceTpuNodeProvider,
                      tpu_slice_node_type)
from .node_provider import LocalNodeProvider, NodeProvider

__all__ = ["Autoscaler", "NodeType", "NodeProvider", "LocalNodeProvider",
           "GceTpuApi", "FakeGceTpuApi", "GceTpuNodeProvider",
           "tpu_slice_node_type"]
