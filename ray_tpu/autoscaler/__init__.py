from .autoscaler import Autoscaler, NodeType
from .node_provider import LocalNodeProvider, NodeProvider

__all__ = ["Autoscaler", "NodeType", "NodeProvider", "LocalNodeProvider"]
