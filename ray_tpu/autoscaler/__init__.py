from .autoscaler import Autoscaler, NodeType
from .gce_tpu import (FakeGceTpuApi, GceTpuApi, GceTpuNodeProvider,
                      tpu_slice_node_type)
from .instance_manager import Instance, InstanceManager
from .node_provider import (FakeFileNodeProvider, LocalNodeProvider,
                            NodeProvider)

__all__ = ["Autoscaler", "NodeType", "NodeProvider", "LocalNodeProvider",
           "FakeFileNodeProvider", "Instance", "InstanceManager",
           "GceTpuApi", "FakeGceTpuApi", "GceTpuNodeProvider",
           "tpu_slice_node_type"]
