"""Declarative autoscaler: reconcile node count against pending demand.

Reference capability: autoscaler v2's reconciler
(reference: python/ray/autoscaler/v2/autoscaler.py:47, scheduler.py,
instance_manager/reconciler.py) consuming the GCS autoscaler-state API
(src/ray/gcs/gcs_autoscaler_state_manager.h), and v1's demand bin-packing
(autoscaler/_private/resource_demand_scheduler.py:100).

Loop: read pending demand from the GCS → bin-pack unplaceable demand onto
configured node types (respecting min/max counts) → create/terminate via the
NodeProvider → repeat. TPU slices scale atomically: a `NodeType` with TPU
resources is created/terminated as one unit, never partially.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.protocol import ConnectionClosed, connect_address
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_nodes: int = 0
    max_nodes: int = 10


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _deduct(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    """One reconciler per cluster, connected to the GCS as a client."""

    def __init__(self, gcs_address: str, provider: NodeProvider,
                 node_types: List[NodeType], *, interval_s: float = 2.0,
                 idle_timeout_s: float = 60.0,
                 node_startup_grace_s: float = 60.0):
        self.provider = provider
        self.node_types = {nt.name: nt for nt in node_types}
        self.interval_s = interval_s
        self.idle_timeout_s = idle_timeout_s
        # launched nodes get this long to join before their capacity stops
        # counting as pending (reference: the resource demand scheduler
        # subtracts launching nodes from unmet demand so each reconcile
        # doesn't relaunch for the same backlog)
        self.node_startup_grace_s = node_startup_grace_s
        self._conn = connect_address(gcs_address)
        self._rid = itertools.count(1)
        self._rpc({"type": "autoscaler_attach"})  # infeasible PGs now pend
        self._nodes: Dict[str, str] = {}  # provider node id → type name
        self._launch_times: Dict[str, float] = {}
        self._idle_since: Dict[str, float] = {}
        # type name → monotonic ts until which launches are suppressed
        # (provider create failed with quota/stockout: hot-retrying cannot
        # succeed, so the failure maps into reconciler state instead of
        # crashing the loop — reference: v2 instance_manager tracks launch
        # failures per instance type)
        self._type_cooldown: Dict[str, float] = {}
        self._launch_errors: Dict[str, str] = {}  # type → last error text
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- GCS I/O -----------------------------------------------------------

    def _rpc(self, msg: dict) -> dict:
        msg["rid"] = next(self._rid)
        self._conn.send(msg)
        while True:
            reply = self._conn.recv()
            if reply.get("rid") == msg["rid"]:
                return reply

    def _demand(self) -> dict:
        return self._rpc({"type": "resource_demand"})["demand"]

    # -- reconciliation ----------------------------------------------------

    def reconcile_once(self) -> dict:
        """One reconcile pass; returns a summary (for tests/introspection)."""
        demand = self._demand()
        actions = {"launched": [], "terminated": []}

        # 1. unplaceable demand = demands that don't fit current availability
        avail = dict(demand["available_resources"])
        unmet: List[Dict[str, float]] = []
        for d in demand["demands"]:
            if _fits(avail, d):
                _deduct(avail, d)
            else:
                unmet.append(d)
        for pg in demand["pg_demands"]:
            for b in pg["bundles"]:
                if _fits(avail, b):
                    _deduct(avail, b)
                else:
                    unmet.append(b)

        # 2. min_nodes floors
        counts: Dict[str, int] = {}
        for nid, tname in self._nodes.items():
            counts[tname] = counts.get(tname, 0) + 1
        for nt in self.node_types.values():
            while (counts.get(nt.name, 0) < nt.min_nodes
                   and not self._cooling_down(nt.name)):
                nid = self._launch(nt)
                if nid is None:
                    break  # cooldown just started; next pass retries
                actions["launched"].append((nt.name, nid))
                counts[nt.name] = counts.get(nt.name, 0) + 1

        # 3. bin-pack unmet demand onto new nodes — several demands may share
        #    one planned node (reference: ResourceDemandScheduler bin-packing).
        #    Recently launched nodes that haven't joined yet are seeded as
        #    pending capacity so the same backlog doesn't relaunch each pass.
        now0 = time.monotonic()
        joined = set(demand.get("node_ids") or ())
        planned: List[tuple] = []  # (NodeType, remaining capacity, is_new)
        for nid, tname in self._nodes.items():
            nt = self.node_types.get(tname)
            if (nt is not None
                    # joined capacity is already in available_resources —
                    # counting it again would absorb real demand into
                    # phantom capacity (providers map ids via node_joined)
                    and not self.provider.node_joined(nid, joined)
                    and now0 - self._launch_times.get(nid, 0.0)
                    < self.node_startup_grace_s):
                planned.append((nt, dict(nt.resources), False))
        for d in sorted(unmet, key=lambda d: -sum(d.values())):
            for _, rem, _new in planned:
                if _fits(rem, d):
                    _deduct(rem, d)
                    break
            else:
                for nt in self.node_types.values():
                    if self._cooling_down(nt.name):
                        continue  # launches of this type just failed
                    count_now = (counts.get(nt.name, 0)
                                 + sum(1 for p, _r, new in planned
                                       if new and p.name == nt.name))
                    if count_now >= nt.max_nodes:
                        continue
                    if _fits(dict(nt.resources), d):
                        rem = dict(nt.resources)
                        _deduct(rem, d)
                        planned.append((nt, rem, True))
                        break
        for nt, _rem, new in planned:
            if not new:
                continue
            if self._cooling_down(nt.name):
                # an earlier launch in THIS pass failed: don't hot-retry
                continue
            nid = self._launch(nt)
            if nid is not None:
                actions["launched"].append((nt.name, nid))

        # 4. terminate idle above-min nodes (no demand and nothing running
        #    on them — approximated by zero unmet demand + full availability)
        if not unmet and not demand["pg_demands"]:
            now = time.monotonic()
            for nid, tname in list(self._nodes.items()):
                nt = self.node_types.get(tname)
                if nt is None:
                    continue
                alive_of_type = sum(1 for t in self._nodes.values() if t == tname)
                if alive_of_type <= nt.min_nodes:
                    self._idle_since.pop(nid, None)
                    continue
                since = self._idle_since.setdefault(nid, now)
                if now - since >= self.idle_timeout_s:
                    self._terminate(nid)
                    actions["terminated"].append((tname, nid))
        else:
            self._idle_since.clear()

        # reap externally-died nodes (incl. preempted slices the provider
        # filters out of non_terminated_nodes — relaunched next pass)
        live = set(self.provider.non_terminated_nodes())
        for nid in list(self._nodes):
            if nid not in live:
                self._nodes.pop(nid, None)
                self._idle_since.pop(nid, None)
                self._launch_times.pop(nid, None)
        # expired cooldowns drop their stale error from the summary too
        for tname in list(self._launch_errors):
            if not self._cooling_down(tname):
                self._launch_errors.pop(tname, None)
        actions["launch_failures"] = dict(self._launch_errors)
        return actions

    def _cooling_down(self, tname: str) -> bool:
        return time.monotonic() < self._type_cooldown.get(tname, 0.0)

    def _launch(self, nt: NodeType) -> Optional[str]:
        """Create a node; on provider failure, back off the node type for
        the error's suggested cooldown and return None instead of raising —
        a quota/stockout must degrade the reconciler, not crash it."""
        try:
            nid = self.provider.create_node(nt.name, nt.resources, nt.labels)
        except Exception as e:
            cooldown = float(getattr(e, "cooldown_s", 10.0))
            self._type_cooldown[nt.name] = time.monotonic() + cooldown
            self._launch_errors[nt.name] = str(e)
            logger.warning("autoscaler: launch of %s failed (%s); cooling "
                           "down %.0fs", nt.name, e, cooldown)
            return None
        self._launch_errors.pop(nt.name, None)
        self._nodes[nid] = nt.name
        self._launch_times[nid] = time.monotonic()
        logger.info("autoscaler: launched %s node %s", nt.name, nid)
        return nid

    def _terminate(self, nid: str) -> None:
        self.provider.terminate_node(nid)
        tname = self._nodes.pop(nid, "?")
        self._idle_since.pop(nid, None)
        self._launch_times.pop(nid, None)
        logger.info("autoscaler: terminated %s node %s", tname, nid)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.reconcile_once()
            except ConnectionClosed:
                return
            except Exception:
                logger.exception("autoscaler reconcile failed")

    def stop(self, terminate_nodes: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if terminate_nodes:
            for nid in list(self._nodes):
                try:
                    self._terminate(nid)
                except Exception:
                    # one failed cloud call must not abort teardown and
                    # leak every REMAINING node
                    logger.exception("failed to terminate node %s", nid)
        try:
            self._conn.close()
        except Exception:
            pass
