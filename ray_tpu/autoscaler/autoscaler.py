"""Declarative autoscaler: reconcile node count against pending demand.

Reference capability: autoscaler v2's reconciler
(reference: python/ray/autoscaler/v2/autoscaler.py:47, scheduler.py,
instance_manager/reconciler.py) consuming the GCS autoscaler-state API
(src/ray/gcs/gcs_autoscaler_state_manager.h), and v1's demand bin-packing
(autoscaler/_private/resource_demand_scheduler.py:100).

Every node the autoscaler touches is an `Instance` record in a persisted
state machine (instance_manager.py): REQUESTED is persisted before the
provider create call, ALLOCATED after it, TERMINATING before the terminate
call — so `reconcile_once` is a pure function of (persisted instance table,
provider `non_terminated_nodes()`, GCS demand). A reconciler SIGKILLed at
any single point restarts, rebuilds from the table, adopts still-alive
provider nodes, reaps records whose node vanished, sweeps provider nodes
that have no record, and converges to the same target without
double-launching or leaking (tests/test_autoscaler_chaos.py).

Loop: read pending demand from the GCS → bin-pack unplaceable demand onto
configured node types (respecting min/max counts) → create/terminate via the
NodeProvider → repeat. TPU slices scale atomically: a `NodeType` with TPU
resources is created/terminated as one unit, never partially.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private.protocol import ConnectionClosed, connect_address
from ray_tpu.autoscaler import instance_manager as im
from ray_tpu.autoscaler.node_provider import NodeProvider

logger = logging.getLogger(__name__)


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    min_nodes: int = 0
    max_nodes: int = 10


def _fits(avail: Dict[str, float], demand: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())


def _deduct(avail: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    """One reconciler per cluster, connected to the GCS as a client."""

    def __init__(self, gcs_address: str, provider: NodeProvider,
                 node_types: List[NodeType], *, interval_s: float = 2.0,
                 idle_timeout_s: float = 60.0,
                 node_startup_grace_s: float = 60.0,
                 drain_grace_s: Optional[float] = None):
        from ray_tpu._private.ray_config import RayConfig

        self.provider = provider
        self.node_types = {nt.name: nt for nt in node_types}
        self.interval_s = interval_s
        self.idle_timeout_s = idle_timeout_s
        # scale-down is drain-then-terminate: the node_drain RPC stops new
        # placements and lets resident train workers grace-checkpoint, and
        # the provider terminate waits out this window (0 = same pass)
        self.drain_grace_s = (RayConfig.get("drain_grace_s")
                              if drain_grace_s is None else float(drain_grace_s))
        # launched nodes get this long to join before their capacity stops
        # counting as pending (reference: the resource demand scheduler
        # subtracts launching nodes from unmet demand so each reconcile
        # doesn't relaunch for the same backlog)
        self.node_startup_grace_s = node_startup_grace_s
        self._conn = connect_address(gcs_address)
        self._rid = itertools.count(1)
        # stop() keeps going after a 5s join timeout (the loop thread may be
        # wedged in a provider backoff) and then issues RPCs of its own:
        # request/reply pairs on the shared connection must be atomic or
        # each thread's recv loop silently eats the other's reply
        self._rpc_lock = threading.Lock()
        self._rpc({"type": "autoscaler_attach"})  # infeasible PGs now pend
        # the persisted instance state machine, write-through to the GCS
        # `instances` table; the first reconcile pass rebuilds from it
        self._im = im.InstanceManager(im.GcsInstanceStorage(self._rpc))
        self._recovered = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- GCS I/O -----------------------------------------------------------

    def _rpc(self, msg: dict) -> dict:
        msg["rid"] = next(self._rid)
        with self._rpc_lock:
            self._conn.send(msg)
            while True:
                reply = self._conn.recv()
                if reply.get("rid") == msg["rid"]:
                    return reply

    def _demand(self) -> dict:
        return self._rpc({"type": "resource_demand"})["demand"]

    # -- metrics -----------------------------------------------------------

    def _observe_pass(self, duration_s: float) -> None:
        """Record reconcile duration + per-type pending/running gauges.
        Gauges are set for EVERY configured node type (zero included) so a
        scale-down is visible as 0, not as a vanished series."""
        try:
            from ray_tpu.util.metrics import (Gauge, Histogram,
                                              get_or_create)

            get_or_create(
                Histogram, "ray_tpu_autoscaler_reconcile_seconds",
                "autoscaler reconcile-pass duration",
                boundaries=(0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0),
            ).observe(duration_s)
            pending = self._im.counts(states=(im.REQUESTED, im.ALLOCATED))
            running = self._im.counts(states=(im.RUNNING, im.IDLE_TRACKED))
            g_pend = get_or_create(
                Gauge, "ray_tpu_autoscaler_pending_nodes",
                "instances requested/allocated but not yet joined",
                tag_keys=("node_type",))
            g_run = get_or_create(
                Gauge, "ray_tpu_autoscaler_running_nodes",
                "instances joined to the cluster (incl. idle-tracked)",
                tag_keys=("node_type",))
            for tname in self.node_types:
                g_pend.set(pending.get(tname, 0), tags={"node_type": tname})
                g_run.set(running.get(tname, 0), tags={"node_type": tname})
        except Exception:  # noqa: BLE001 — metrics must never fail a pass
            logger.debug("autoscaler metrics update failed", exc_info=True)

    def _flush_metrics(self) -> None:
        """Ship this process's metric registry to the GCS. Only when no
        in-process CoreWorker exists (the monitor process): a driver-hosted
        autoscaler shares the process registry, which the driver's own
        flusher already reports — a second source would double-count."""
        try:
            from ray_tpu._private import api as _api

            if getattr(_api, "_worker", None) is not None:
                return
            from ray_tpu.util import metrics as _met

            snap = _met.snapshot()
            if not snap:
                return
            # source is per-PROCESS (registry is process-wide): a restarted
            # Autoscaler instance in the same monitor re-reports the same
            # cumulative registry, and per-source replace must not let the
            # GCS sum the old and new copies
            import os as _os

            msg = {"type": "metrics_report",
                   "source": f"autoscaler:{_os.getpid()}", "metrics": snap}
            with self._rpc_lock:  # one-way send; metrics_report never replies
                self._conn.send(msg)
        except Exception:  # noqa: BLE001
            pass

    # -- reconciliation ----------------------------------------------------

    def reconcile_once(self) -> dict:
        """One reconcile pass; returns a summary (for tests/introspection)."""
        t_pass = time.monotonic()
        try:
            return self._reconcile_once()
        finally:
            self._observe_pass(time.monotonic() - t_pass)

    def _reconcile_once(self) -> dict:
        actions = {"launched": [], "terminated": [], "adopted": [],
                   "reaped": [], "swept": [], "drained": []}
        if not self._recovered:
            self._recover(actions)
            self._recovered = True
        now = time.time()

        # 0. sync the table against provider ground truth. This is what
        #    makes a restart just another pass: stale records resolve, and
        #    provider reality the table doesn't know about gets cleaned up.
        live = set(self.provider.non_terminated_nodes())
        for inst in self._im.instances(im.REQUESTED):
            # only a crashed reconciler leaves REQUESTED behind (within a
            # pass it resolves synchronously): launch outcome unknown, so
            # count it failed — any node it DID create has no record and is
            # swept below, and real demand drives a fresh launch
            self._im.transition(inst, im.TERMINATED)
        for inst in self._im.instances(*im.LIVE_STATES):
            if inst.node_id not in live:
                # externally-died node (incl. preempted slices the provider
                # filters out of non_terminated_nodes — relaunched on demand)
                self._im.transition(inst, im.TERMINATED)
                actions["reaped"].append((inst.node_type, inst.node_id))
        for inst in self._im.instances(im.TERMINATING):
            if inst.node_id in live:
                # crash landed between the TERMINATING persist and the cloud
                # call: re-issue the (idempotent) terminate
                if self._terminate_instance(inst, actions):
                    live.discard(inst.node_id)  # or the sweep re-terminates
            else:
                self._im.transition(inst, im.TERMINATED)
        for inst in self._im.instances(im.ALLOCATION_FAILED):
            if now >= inst.cooldown_until:
                # expired cooldowns drop their stale error from the summary
                self._im.transition(inst, im.TERMINATED)
        # leak sweep: provider nodes no record claims. Only nodes the
        # provider recognizes as autoscaler-created (owns_node) — sweeping a
        # foreign node would be worse than leaking one.
        recorded = {i.node_id for i in self._im.instances() if i.node_id}
        for nid in sorted(live - recorded):
            if not self.provider.owns_node(nid):
                continue
            try:
                self.provider.terminate_node(nid)
                actions["swept"].append(nid)
                live.discard(nid)
                logger.warning("autoscaler: swept leaked node %s (no "
                               "instance record)", nid)
            except Exception:
                logger.exception("failed to sweep leaked node %s", nid)

        demand = self._demand()
        joined = set(demand.get("node_ids") or ())
        for inst in self._im.instances(im.ALLOCATED):
            if inst.node_id in live and self.provider.node_joined(
                    inst.node_id, joined):
                self._im.transition(inst, im.RUNNING)

        # 1. unplaceable demand = demands that don't fit current availability
        avail = dict(demand["available_resources"])
        unmet: List[Dict[str, float]] = []
        for d in demand["demands"]:
            if _fits(avail, d):
                _deduct(avail, d)
            else:
                unmet.append(d)
        for pg in demand["pg_demands"]:
            for b in pg["bundles"]:
                if _fits(avail, b):
                    _deduct(avail, b)
                else:
                    unmet.append(b)

        # 2. min_nodes floors
        counts = self._im.counts()
        for nt in self.node_types.values():
            while (counts.get(nt.name, 0) < nt.min_nodes
                   and not self._cooling_down(nt.name)):
                nid = self._launch(nt)
                if nid is None:
                    break  # cooldown just started; next pass retries
                actions["launched"].append((nt.name, nid))
                counts[nt.name] = counts.get(nt.name, 0) + 1

        # 3. bin-pack unmet demand onto new nodes — several demands may share
        #    one planned node (reference: ResourceDemandScheduler bin-packing).
        #    ALLOCATED instances that haven't joined yet are seeded as
        #    pending capacity so the same backlog doesn't relaunch each pass
        #    (their launch_time is persisted wall-clock: the seeding — and
        #    therefore double-launch protection — survives a restart).
        planned: List[tuple] = []  # (NodeType, remaining capacity, is_new)
        for inst in self._im.instances(im.ALLOCATED):
            nt = self.node_types.get(inst.node_type)
            if (nt is not None
                    # joined capacity is already in available_resources —
                    # counting it again would absorb real demand into
                    # phantom capacity (ALLOCATED means not yet joined)
                    and now - inst.launch_time < self.node_startup_grace_s):
                planned.append((nt, dict(nt.resources), False))
        for d in sorted(unmet, key=lambda d: -sum(d.values())):
            for _, rem, _new in planned:
                if _fits(rem, d):
                    _deduct(rem, d)
                    break
            else:
                for nt in self.node_types.values():
                    if self._cooling_down(nt.name):
                        continue  # launches of this type just failed
                    count_now = (counts.get(nt.name, 0)
                                 + sum(1 for p, _r, new in planned
                                       if new and p.name == nt.name))
                    if count_now >= nt.max_nodes:
                        continue
                    if _fits(dict(nt.resources), d):
                        rem = dict(nt.resources)
                        _deduct(rem, d)
                        planned.append((nt, rem, True))
                        break
        for nt, _rem, new in planned:
            if not new:
                continue
            if self._cooling_down(nt.name):
                # an earlier launch in THIS pass failed: don't hot-retry
                continue
            nid = self._launch(nt)
            if nid is not None:
                actions["launched"].append((nt.name, nid))

        # 4. drain-then-terminate idle above-min nodes (no demand and nothing
        #    running on them — approximated by zero unmet demand + full
        #    availability). Idle past the timeout → DRAINING (the GCS stops
        #    placing there; resident train workers grace-checkpoint) →
        #    terminate once the drain window elapses.
        if not unmet and not demand["pg_demands"]:
            live_insts = self._im.instances(*im.LIVE_STATES)
            alive_counts = self._im.counts(states=im.LIVE_STATES)
            for inst in live_insts:
                nt = self.node_types.get(inst.node_type)
                if nt is None:
                    continue
                if inst.state == im.DRAINING:
                    # drain is one-way — even below the min floor the node
                    # is already unplaceable, so terminate on schedule and
                    # let the min-floor step relaunch a fresh one
                    if now >= inst.drain_deadline:
                        if self._terminate_instance(inst, actions):
                            alive_counts[inst.node_type] = (
                                alive_counts.get(inst.node_type, 1) - 1)
                    continue
                if alive_counts.get(inst.node_type, 0) <= nt.min_nodes:
                    if inst.state == im.IDLE_TRACKED:
                        self._im.transition(inst, im.RUNNING, idle_since=None)
                    continue
                if (inst.state == im.ALLOCATED
                        and now - inst.launch_time
                        < self.node_startup_grace_s):
                    # a just-launched node that hasn't joined yet must not be
                    # idle-terminated out from under its own startup: the
                    # idle clock only starts once it joins (RUNNING) or
                    # overstays the startup grace
                    continue
                if inst.state != im.IDLE_TRACKED:
                    inst = self._im.transition(inst, im.IDLE_TRACKED,
                                               idle_since=now)
                if now - (inst.idle_since or now) >= self.idle_timeout_s:
                    inst = self._drain_instance(inst, now, actions)
                    if inst.state == im.DRAINING and now >= inst.drain_deadline:
                        # grace 0: terminate in the same pass
                        if self._terminate_instance(inst, actions):
                            alive_counts[inst.node_type] = (
                                alive_counts.get(inst.node_type, 1) - 1)
        else:
            for inst in self._im.instances(im.IDLE_TRACKED):
                self._im.transition(inst, im.RUNNING, idle_since=None)
            # demand cannot un-drain a node (the GCS-side flag is sticky):
            # holding a DRAINING node would just strand unusable capacity
            for inst in self._im.instances(im.DRAINING):
                if now >= inst.drain_deadline:
                    self._terminate_instance(inst, actions)

        actions["launch_failures"] = {
            f.node_type: f.error
            for f in self._im.instances(im.ALLOCATION_FAILED)}
        return actions

    def _recover(self, actions: dict) -> None:
        """Startup rebuild: load the persisted table and let the provider
        re-attach to each recorded live node (a fresh LocalNodeProvider
        re-adopts agent pids; cloud providers just confirm existence).
        Records whose node is truly gone are reaped by the sync step of the
        same pass — recovery never launches or terminates by itself."""
        for inst in self._im.load():
            # TERMINATING is included: a terminate interrupted by the crash
            # must be re-attachable, or a provider whose visibility depends
            # on adoption (LocalNodeProvider pids) would "lose" the node and
            # orphan it instead of re-issuing the terminate
            if inst.state not in (*im.LIVE_STATES, im.TERMINATING):
                continue
            adopted = False
            try:
                adopted = self.provider.adopt_node(
                    inst.node_id, dict(inst.provider_data))
            except Exception:
                logger.exception("adopt_node failed for %s", inst.node_id)
            if adopted:
                actions["adopted"].append((inst.node_type, inst.node_id))
                logger.info("autoscaler: adopted %s node %s from persisted "
                            "state", inst.node_type, inst.node_id)

    def _cooling_down(self, tname: str) -> bool:
        now = time.time()
        return any(f.cooldown_until > now
                   for f in self._im.instances(im.ALLOCATION_FAILED)
                   if f.node_type == tname)

    def _launch(self, nt: NodeType) -> Optional[str]:
        """Create a node; on provider failure, back off the node type for
        the error's suggested cooldown and return None instead of raising —
        a quota/stockout must degrade the reconciler, not crash it.

        Persistence ordering: the REQUESTED record is durable BEFORE the
        provider call, the ALLOCATED record (with the node id) right after
        it — a crash at any point leaves a record the recovery sweep can
        resolve."""
        if self._stop.is_set():
            # a wedged reconcile pass resuming AFTER stop() tore the fleet
            # down must not relaunch nodes nobody will ever terminate
            return None
        inst = self._im.create(nt.name)
        try:
            nid = self.provider.create_node(nt.name, nt.resources, nt.labels)
        except Exception as e:
            cooldown = float(getattr(e, "cooldown_s", 10.0))
            self._im.transition(inst, im.ALLOCATION_FAILED,
                                cooldown_until=time.time() + cooldown,
                                error=str(e))
            logger.warning("autoscaler: launch of %s failed (%s); cooling "
                           "down %.0fs", nt.name, e, cooldown)
            return None
        if self._stop.is_set():
            # stop() tore the fleet down while this create was in flight
            # (thread wedged inside the provider call past the join
            # timeout): ALLOCATING now would hand a live node to nobody —
            # undo it instead
            logger.warning("autoscaler: launch of %s completed after stop; "
                           "terminating %s", nt.name, nid)
            try:
                self.provider.terminate_node(nid)
            except Exception:
                logger.exception("failed to terminate post-stop node %s",
                                 nid)
            try:
                self._im.transition(inst, im.TERMINATED)
            except Exception:
                # GCS may already be gone; a stale REQUESTED record is
                # resolved by the next incarnation's recovery
                pass
            return None
        data: dict = {}
        try:
            data = self.provider.describe_node(nid) or {}
        except Exception:
            logger.exception("describe_node failed for %s", nid)
        self._im.transition(inst, im.ALLOCATED, node_id=nid,
                            launch_time=time.time(), provider_data=data)
        # a successful launch retires stale failure records of this type
        for f in self._im.instances(im.ALLOCATION_FAILED):
            if f.node_type == nt.name:
                self._im.transition(f, im.TERMINATED)
        logger.info("autoscaler: launched %s node %s", nt.name, nid)
        return nid

    def _drain_instance(self, inst: im.Instance, now: float,
                        actions: dict) -> im.Instance:
        """Begin drain-then-terminate: DRAINING (with its deadline) is
        durable BEFORE the node_drain RPC flips GCS state — a crash in
        between re-enters here with the flag already persisted, and the
        (idempotent) drain is simply re-issued by the sticky GCS record."""
        if inst.state == im.DRAINING:
            return inst
        inst = self._im.transition(inst, im.DRAINING,
                                   drain_deadline=now + self.drain_grace_s)
        try:
            reply = self._rpc({"type": "node_drain", "node_id": inst.node_id,
                               "grace_s": self.drain_grace_s,
                               "reason": "autoscaler scale-down"})
            if not reply.get("ok"):
                # provider-known but never joined the GCS: nothing to notify
                logger.debug("node_drain for %s declined: %s", inst.node_id,
                             reply.get("error"))
        except ConnectionClosed:
            logger.warning("node_drain RPC failed for %s (GCS gone); "
                           "terminating on schedule anyway", inst.node_id)
        actions["drained"].append((inst.node_type, inst.node_id))
        logger.info("autoscaler: draining %s node %s (grace %.0fs)",
                    inst.node_type, inst.node_id, self.drain_grace_s)
        return inst

    def _terminate_instance(self, inst: im.Instance, actions: dict) -> bool:
        """TERMINATING is durable before the cloud call: a crash in between
        re-issues the idempotent terminate on restart instead of leaking.
        Returns True once the node is actually gone."""
        if inst.state != im.TERMINATING:
            inst = self._im.transition(inst, im.TERMINATING)
        try:
            self.provider.terminate_node(inst.node_id)
        except Exception:
            # record stays TERMINATING; the next pass re-issues
            logger.exception("failed to terminate node %s", inst.node_id)
            return False
        self._im.transition(inst, im.TERMINATED)
        actions["terminated"].append((inst.node_type, inst.node_id))
        logger.info("autoscaler: terminated %s node %s", inst.node_type,
                    inst.node_id)
        return True

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.reconcile_once()
                self._flush_metrics()
            except ConnectionClosed:
                return
            except Exception:
                logger.exception("autoscaler reconcile failed")

    def stop(self, terminate_nodes: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # a loop thread still alive after the join timeout may be wedged
        # inside an RPC holding _rpc_lock — teardown must not touch the
        # shared connection or it deadlocks
        wedged = self._thread is not None and self._thread.is_alive()
        if terminate_nodes and not self._recovered and not wedged:
            # stopped before the first reconcile ever ran: the in-memory
            # view is empty but the TABLE may hold a previous incarnation's
            # live nodes — load (and adopt, so pid-based providers can kill
            # them) or terminate_nodes would silently leak everything
            try:
                self._recover({"adopted": []})
                self._recovered = True
            except Exception:
                logger.warning("could not load persisted instances for "
                               "teardown (GCS gone?)")
        if terminate_nodes:
            # teardown is provider-FIRST, persistence best-effort — the
            # inverse of the reconcile-path ordering. The monitor often
            # stops BECAUSE the head/GCS died (ConnectionClosed exit), and
            # a failing persist must not stand between us and releasing
            # cloud nodes. A record left stale here still resolves: the
            # next reconciler's sync reaps it once the node is gone.
            # InstanceManager snapshots are internally locked, so this is
            # consistent even against a wedged reconcile thread mid-pass
            for inst in self._im.instances(*im.LIVE_STATES, im.TERMINATING):
                try:
                    self.provider.terminate_node(inst.node_id)
                except Exception:
                    # one failed cloud call must not abort teardown and
                    # leak every REMAINING node
                    logger.exception("failed to terminate node %s",
                                     inst.node_id)
                    continue
                logger.info("autoscaler: terminated %s node %s",
                            inst.node_type, inst.node_id)
                if wedged:
                    continue
                try:
                    if inst.state != im.TERMINATING:
                        inst = self._im.transition(inst, im.TERMINATING)
                    self._im.transition(inst, im.TERMINATED)
                except Exception:
                    logger.warning("could not persist teardown of %s "
                                   "(GCS gone?); the recovery sweep will "
                                   "resolve the stale record", inst.node_id)
        try:
            self._conn.close()
        except Exception:
            pass
