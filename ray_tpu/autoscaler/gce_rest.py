"""REST client for the GCE Cloud TPU API (tpu.googleapis.com v2).

Implements the `GceTpuApi` surface the slice-atomic provider consumes
(reference: python/ray/autoscaler/_private/gcp/node.py GCPTPUNode wraps the
same API via googleapiclient; tpu_command_runner.py drives the created pod).
Built on urllib with an injectable transport so every path — retries,
backoff, quota/stockout/preemption mapping — is testable offline against
canned responses; production uses the default transport + the GCE metadata
server for tokens.

Error model (surfaced to the autoscaler reconciler):
- `QuotaExceededError`  — 403/429 with quota/rate messages: backoff the
  node type; retrying immediately cannot succeed.
- `StockoutError`       — RESOURCE_EXHAUSTED / "no available capacity" in
  zone: backoff the node type, ideally try another zone.
- `TpuApiError`         — anything else non-retryable (4xx).
Transient 5xx/429 responses and transport failures are retried here with
exponential backoff before any error escapes.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.gce_tpu import GceTpuApi

_BASE = "https://tpu.googleapis.com/v2"
_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

# node states the API reports that mean "this slice is gone or dying":
# preempted/terminated slices must drop out of non_terminated_nodes so the
# reconciler reaps and relaunches them
_TERMINAL_STATES = {"PREEMPTED", "TERMINATED", "HIDING", "HIDDEN", "DELETING"}

# google.rpc.Code numeric → name (the subset operation errors carry)
_RPC_CODES = {3: "INVALID_ARGUMENT", 5: "NOT_FOUND", 7: "PERMISSION_DENIED",
              8: "RESOURCE_EXHAUSTED", 13: "INTERNAL", 14: "UNAVAILABLE"}


class TpuApiError(Exception):
    """Non-retryable TPU API failure (final status + parsed message)."""

    def __init__(self, status: int, message: str):
        self.status = status
        self.message = message
        super().__init__(f"TPU API error {status}: {message}")


class QuotaExceededError(TpuApiError):
    """Project quota exhausted — backoff, don't hot-retry."""

    cooldown_s = 120.0  # the reconciler backs off this node type


class StockoutError(TpuApiError):
    """Zone has no capacity for this accelerator right now."""

    cooldown_s = 30.0  # stockouts churn; re-probe sooner than quota


def _default_transport(method: str, url: str, headers: Dict[str, str],
                       body: Optional[bytes], timeout: float):
    """(status_code, body_bytes) via urllib; HTTP errors return their
    status instead of raising so the retry loop can classify them."""
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def metadata_token_provider() -> str:
    """Access token from the GCE metadata server (VMs with a service
    account). Off-GCP deployments inject their own provider."""
    status, body = _default_transport(
        "GET", _METADATA_TOKEN_URL, {"Metadata-Flavor": "Google"}, None, 5.0)
    if status != 200:
        raise TpuApiError(status, "metadata server token fetch failed")
    return json.loads(body)["access_token"]


def _error_message(body: bytes) -> tuple[str, str]:
    """(message, rpc_status) from a google.rpc error envelope."""
    try:
        err = json.loads(body or b"{}").get("error") or {}
        return str(err.get("message") or ""), str(err.get("status") or "")
    except Exception:
        return (body or b"")[:200].decode("utf-8", "replace"), ""


def classify_error(status: int, body: bytes) -> TpuApiError:
    """Map a final (post-retry) HTTP failure to the typed error the
    reconciler keys its backoff decisions on."""
    msg, rpc = _error_message(body)
    low = msg.lower()
    if rpc == "RESOURCE_EXHAUSTED" or "no available capacity" in low \
            or "stockout" in low or "resources are insufficient" in low:
        # quota wording wins: quota problems persist, stockouts churn
        if "quota" not in low:
            return StockoutError(status, msg or "zone stockout")
    if status in (403, 429) and ("quota" in low or "rate limit" in low
                                 or rpc == "RESOURCE_EXHAUSTED"):
        return QuotaExceededError(status, msg or "quota exceeded")
    return TpuApiError(status, msg or f"http {status}")


class RestGceTpuApi(GceTpuApi):
    """GceTpuApi over tpu.googleapis.com v2 nodes.{create,delete,list,get}.

    `transport(method, url, headers, body, timeout) -> (status, bytes)` and
    `token_provider() -> str` are injectable; tests drive canned responses
    through exactly the code paths production takes.
    """

    RETRYABLE = {429, 500, 502, 503, 504}

    def __init__(self, project: str, zone: str, *,
                 token_provider: Callable[[], str] | None = None,
                 transport=_default_transport,
                 gcs_address: str = "",
                 runtime_version: str = "tpu-ubuntu2204-base",
                 network: str = "", preemptible: bool = False,
                 max_retries: int = 4, timeout_s: float = 30.0,
                 backoff_s: float = 0.5, op_polls: int = 3,
                 op_poll_s: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.project = project
        self.zone = zone
        self.token_provider = token_provider
        self.transport = transport
        self.gcs_address = gcs_address
        self.runtime_version = runtime_version
        self.network = network
        self.preemptible = preemptible
        self.max_retries = max_retries
        self.timeout_s = timeout_s
        self.backoff_s = backoff_s
        self.op_polls = op_polls
        self.op_poll_s = op_poll_s
        # injectable for deterministic jitter tests
        self._rng = rng if rng is not None else random.Random()
        self._token: Optional[str] = None

    # -- plumbing ----------------------------------------------------------

    def validate(self) -> None:
        """Startup credential probe: obtain one access token NOW so a
        misconfigured deployment fails at `ray_tpu start`/monitor launch
        with an actionable error, not at the first scale-up (reference:
        providers validate credentials at autoscaler boot)."""
        try:
            self._headers()
        except Exception as e:
            raise RuntimeError(
                f"gce_tpu provider cannot obtain an access token for "
                f"project={self.project!r} zone={self.zone!r}: {e}. On GCE "
                "the metadata server supplies it; elsewhere pass a "
                "token_provider (e.g. from service-account credentials)."
            ) from e

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _headers(self) -> Dict[str, str]:
        if self._token is None:
            # late-bound default: resolving the module attribute at CALL
            # time keeps the metadata fallback monkeypatchable/testable
            provider = self.token_provider or metadata_token_provider
            self._token = provider()
        return {"Authorization": f"Bearer {self._token}",
                "Content-Type": "application/json"}

    def _call(self, method: str, path: str, *, query: str = "",
              body: Optional[dict] = None) -> dict:
        url = f"{_BASE}/{path}" + (f"?{query}" if query else "")
        payload = json.dumps(body).encode() if body is not None else None
        delay = self.backoff_s
        last: tuple[int, bytes] = (0, b"")
        refreshed = False
        for attempt in range(self.max_retries + 1):
            try:
                status, data = self.transport(
                    method, url, self._headers(), payload, self.timeout_s)
            except Exception:
                # transport-level failure (DNS, reset): retryable
                status, data = (0, b"")
            if 200 <= status < 300:
                return json.loads(data or b"{}")
            last = (status, data)
            if status == 401 and not refreshed:
                # expired token: refresh once per call and retry immediately
                self._token = None
                refreshed = True
                continue
            if status in self.RETRYABLE or status == 0:
                err = classify_error(status, data)
                if isinstance(err, (QuotaExceededError, StockoutError)):
                    # a hard no — retrying (and sleeping) cannot help; the
                    # reconciler's type cooldown takes it from here
                    raise err
                if attempt < self.max_retries:
                    # full jitter over the exponential window (the
                    # retry/backoff+jitter convention from train/storage.py):
                    # many reconcilers retrying the same quota/5xx must not
                    # hammer the API in lockstep at deterministic delays
                    time.sleep(self._rng.uniform(0.0, delay))
                    delay = min(delay * 2, 30.0)
                    continue
            break
        raise classify_error(*last)

    # -- GceTpuApi surface -------------------------------------------------

    def create_node(self, name: str, accelerator_type: str,
                    labels: Dict[str, str]) -> None:
        # GCE label values: lowercase alnum + dash/underscore only
        clean = {str(k).lower().replace("/", "-").replace(".", "-"):
                 str(v).lower().replace("/", "-").replace(".", "-")
                 for k, v in labels.items()}
        body = {
            "acceleratorType": accelerator_type,
            "runtimeVersion": self.runtime_version,
            "labels": clean,
            "schedulingConfig": {"preemptible": self.preemptible},
            "metadata": {
                # every host of the slice self-joins the cluster on boot
                # (reference: tpu_command_runner.py runs setup on all pod
                # workers); -w$(worker-id) keys node_joined's prefix match
                "startup-script": (
                    "#! /bin/bash\n"
                    f"python -m ray_tpu.scripts.cli start "
                    f"--address {self.gcs_address} "
                    f"--host-id {name}-w$(curl -sH 'Metadata-Flavor: Google' "
                    "http://metadata.google.internal/computeMetadata/v1/"
                    "instance/attributes/agent-worker-number)\n"
                ) if self.gcs_address else "",
            },
        }
        if self.network:
            body["networkConfig"] = {"network": self.network}
        op = self._call("POST", f"{self._parent}/nodes",
                        query=f"nodeId={name}", body=body)
        self._check_operation(op)

    def _check_operation(self, op: dict) -> None:
        """nodes.create returns a long-running Operation; async failures
        (the common stockout mode: HTTP 200, then the op fails with
        RESOURCE_EXHAUSTED) must surface through the same quota/stockout
        classification as synchronous errors, or the reconciler relaunches
        every pass with no cooldown. Polls briefly; an op still running
        after the budget is treated as success — the node shows up as
        CREATING and state polling takes over."""
        name = op.get("name")
        for i in range(self.op_polls + 1):
            if op.get("done"):
                err = op.get("error") or {}
                if err:
                    status = {"RESOURCE_EXHAUSTED": 429,
                              "PERMISSION_DENIED": 403,
                              "NOT_FOUND": 404}.get(
                                  _RPC_CODES.get(err.get("code")), 400)
                    raise classify_error(status, json.dumps(
                        {"error": {"message": err.get("message", ""),
                                   "status": _RPC_CODES.get(err.get("code"),
                                                            "")}}).encode())
                return
            if not name or i == self.op_polls:
                return  # budget spent while still running: let state polling decide
            time.sleep(self.op_poll_s)
            op = self._call("GET", str(name).lstrip("/"))

    def delete_node(self, name: str) -> None:
        try:
            self._call("DELETE", f"{self._parent}/nodes/{name}")
        except TpuApiError as e:
            if e.status == 404:
                return  # already gone — deletion is idempotent
            raise

    def list_nodes(self) -> List[str]:
        names: List[str] = []
        page = ""
        while True:
            q = "pageSize=100" + (f"&pageToken={page}" if page else "")
            resp = self._call("GET", f"{self._parent}/nodes", query=q)
            for node in resp.get("nodes") or ():
                if node.get("state") in _TERMINAL_STATES:
                    continue  # preempted/terminated: reconciler must relaunch
                # API returns fully-qualified names
                names.append(str(node.get("name", "")).rsplit("/", 1)[-1])
            page = resp.get("nextPageToken") or ""
            if not page:
                return names

    def node_state(self, name: str) -> str:
        try:
            resp = self._call("GET", f"{self._parent}/nodes/{name}")
        except TpuApiError as e:
            if e.status == 404:
                return "ABSENT"
            raise
        state = str(resp.get("state") or "")
        if state in ("PREEMPTED", "TERMINATED"):
            return "ABSENT"
        if state in ("CREATING", "READY", "DELETING"):
            return state
        if state in ("REPAIRING", "RESTARTING", "STARTING"):
            return "CREATING"
        return "CREATING" if state else "ABSENT"
