"""GCE TPU node provider: a TPU slice is the atomic scaling unit.

Maps the autoscaler's create/terminate/list interface onto the GCE TPU API
(tpu.googleapis.com node operations). A provider "node" is an entire slice
(e.g. v5litepod-16 = 4 hosts x 4 chips): slices are allocated and released
whole, never host-by-host — the slice-head resource (`TPU-<type>-head`)
drives demand so one pending multi-host TPU job launches exactly one slice.

The API surface is injected (`GceTpuApi`): production uses the REST client
(`gce_rest.RestGceTpuApi` — tpu.googleapis.com v2 with retry/backoff and
quota/stockout/preemption mapping); `FakeGceTpuApi` simulates async
provisioning (CREATING → READY) and records calls for fast tests — the
same env-simulation strategy the TPU detection layer uses.

(reference: python/ray/autoscaler/_private/gcp/ — node.py's GCPTPUNode +
tpu_command_runner.py treat one TPU pod as a unit; autoscaler/v2
cloud_providers/* define the same create/terminate/list surface —
VERDICT round-2 item 9.)
"""

from __future__ import annotations

import re
import threading
import time
import uuid
from typing import Dict, List

from ray_tpu.autoscaler.autoscaler import NodeType
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.util.accelerators.tpu import slice_head_resource

# accelerator_type → (chips per slice, hosts per slice)
_SLICE_SHAPES = {
    "v4-8": (4, 1), "v4-16": (8, 2), "v4-32": (16, 4),
    "v5litepod-4": (4, 1), "v5litepod-8": (8, 2), "v5litepod-16": (16, 4),
    "v5litepod-32": (32, 8), "v5litepod-64": (64, 16),
    "v5p-8": (4, 1), "v5p-16": (8, 2),
    "v6e-4": (4, 1), "v6e-8": (8, 2), "v6e-16": (16, 4),
}


def slice_shape(accelerator_type: str) -> tuple[int, int]:
    """(total chips, hosts) for an accelerator type. Fallback parsing
    follows the GCE naming convention: v4/v5p suffixes count TensorCores
    (2 per chip), v5litepod/v6e suffixes count chips; 4 chips per host."""
    if accelerator_type in _SLICE_SHAPES:
        return _SLICE_SHAPES[accelerator_type]
    m = re.search(r"-(\d+)$", accelerator_type)
    if not m:
        raise ValueError(f"unknown accelerator_type {accelerator_type!r}")
    n = int(m.group(1))
    chips = n // 2 if accelerator_type.startswith(("v4-", "v5p-")) else n
    chips = max(1, chips)
    return chips, max(1, chips // 4)


def tpu_slice_node_type(accelerator_type: str, *, cpus_per_host: float = 96.0,
                        min_nodes: int = 0, max_nodes: int = 4) -> NodeType:
    """A NodeType whose resources describe ONE whole slice, including the
    slice-head resource multi-host TPU jobs schedule against."""
    chips, hosts = slice_shape(accelerator_type)
    return NodeType(
        name=f"tpu-{accelerator_type}",
        resources={"TPU": float(chips), "CPU": cpus_per_host * hosts,
                   slice_head_resource(accelerator_type): 1.0},
        labels={"accelerator_type": accelerator_type,
                "ray.io/node-group": f"tpu-{accelerator_type}"},
        min_nodes=min_nodes, max_nodes=max_nodes)


class GceTpuApi:
    """The GCE TPU API surface the provider consumes. Production: REST
    calls against tpu.googleapis.com v2 (nodes.create/delete/list/get)."""

    def create_node(self, name: str, accelerator_type: str,
                    labels: Dict[str, str]) -> None:
        raise NotImplementedError

    def delete_node(self, name: str) -> None:
        raise NotImplementedError

    def list_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_state(self, name: str) -> str:
        """CREATING | READY | DELETING | ABSENT"""
        raise NotImplementedError


class FakeGceTpuApi(GceTpuApi):
    """In-memory GCE TPU API with async CREATING→READY provisioning."""

    def __init__(self, provision_delay_s: float = 0.0):
        self.provision_delay_s = provision_delay_s
        self.nodes: Dict[str, dict] = {}
        self.calls: List[tuple] = []
        self._lock = threading.Lock()

    def create_node(self, name, accelerator_type, labels):
        with self._lock:
            self.calls.append(("create", name, accelerator_type))
            self.nodes[name] = {"accelerator_type": accelerator_type,
                                "labels": dict(labels),
                                "created": time.monotonic()}

    def delete_node(self, name):
        with self._lock:
            self.calls.append(("delete", name))
            self.nodes.pop(name, None)

    def list_nodes(self):
        with self._lock:
            return list(self.nodes)

    def node_state(self, name):
        with self._lock:
            info = self.nodes.get(name)
            if info is None:
                return "ABSENT"
            if time.monotonic() - info["created"] < self.provision_delay_s:
                return "CREATING"
            return "READY"


class GceTpuNodeProvider(NodeProvider):
    """Slice-atomic provider over a GceTpuApi client.

    In production the slice's VMs self-join the cluster: the create request
    carries a startup script running `ray_tpu start --address <gcs>` on
    every host (reference: tpu_command_runner.py runs setup on all workers
    of a pod). The provider itself only manages slice lifecycle."""

    def __init__(self, api: GceTpuApi, *, project: str = "proj",
                 zone: str = "us-central2-b", gcs_address: str = "",
                 cluster_name: str = ""):
        self.api = api
        self.project = project
        self.zone = zone
        self.gcs_address = gcs_address
        # scopes node NAMES (ray--<cluster>--...) and therefore owns_node /
        # the reconciler's leak sweep: set it whenever more than one
        # ray_tpu cluster can share a project+zone, or each reconciler
        # would sweep the other's unrecorded slices
        if "--" in cluster_name or cluster_name.strip("-") != cluster_name:
            # the double hyphen DELIMITS the cluster token in node names;
            # a name containing '--' (or edged with '-', which recreates a
            # '--' at the delimiter) would make "a" own "a--b"'s or "a-"'s
            # slices — the prefix-ambiguity the delimiter exists to prevent
            raise ValueError(
                f"cluster_name {cluster_name!r} must not contain '--' or "
                "begin/end with '-'")
        self.cluster_name = cluster_name
        self._types: Dict[str, str] = {}  # node name → accelerator_type

    @property
    def _name_prefix(self) -> str:
        # '--' delimiters make the scope prefix-unambiguous: 'ray--prod--'
        # can never prefix 'ray--prod-eu--...' (cluster names cannot
        # contain '--', enforced above)
        return f"ray--{self.cluster_name}--" if self.cluster_name else "ray-"

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        acc = labels.get("accelerator_type") or node_type.removeprefix("tpu-")
        name = f"{self._name_prefix}{node_type}-{uuid.uuid4().hex[:6]}"
        self.api.create_node(name, acc, labels)
        self._types[name] = acc
        return name

    def terminate_node(self, node_id: str) -> None:
        self.api.delete_node(node_id)
        self._types.pop(node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        return self.api.list_nodes()

    def is_ready(self, node_id: str) -> bool:
        return self.api.node_state(node_id) == "READY"

    def describe_node(self, node_id: str) -> dict:
        return {"accelerator_type": self._types.get(node_id, "")}

    def adopt_node(self, node_id: str, data: dict) -> bool:
        """A restarted reconciler re-attaches to a slice its predecessor
        created: confirm the node still exists and restore the name →
        accelerator_type mapping from the persisted instance record."""
        if self.api.node_state(node_id) == "ABSENT":
            return False
        acc = data.get("accelerator_type")
        if acc:
            self._types[node_id] = acc
        return True

    def owns_node(self, node_id: str) -> bool:
        """Leak-sweep eligibility requires an explicit cluster_name scope:
        list_nodes sees the whole project+zone, and an UNSCOPED provider
        cannot distinguish its own `ray-...` slices from another cluster's
        `ray-<other>-...` — so it never claims any (leaking a slice is
        recoverable; sweeping a foreign cluster's live slice is not)."""
        return bool(self.cluster_name) and node_id.startswith(
            self._name_prefix)

    def node_joined(self, node_id: str, gcs_node_ids) -> bool:
        """Slice VMs register host ids prefixed with the slice name (the
        startup script passes --host-id <slice-name>-w<k>), so joined-ness
        is a "<name>-w" prefix match — the separator keeps slice "tpu-1"
        from matching hosts of slice "tpu-10"."""
        return any(str(g).startswith(node_id + "-w") for g in gcs_node_ids)
