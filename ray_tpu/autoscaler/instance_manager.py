"""Persisted instance state machine for the autoscaler reconciler.

Reference capability: autoscaler v2's instance manager
(reference: python/ray/autoscaler/v2/instance_manager/instance_manager.py +
instance_storage.py — every node the autoscaler touches is an Instance
record whose state transitions are validated and write-through persisted,
so a restarted reconciler rebuilds from the table instead of from memory).

States:

    REQUESTED ──→ ALLOCATED ──→ RUNNING ──→ IDLE_TRACKED ──→ DRAINING ──→ TERMINATING
        │             │            ↑ ↓            │               │            │
        │             └────────────┼─┴────────────┘               │            ↓
        ↓                          │                              │        TERMINATED
    ALLOCATION_FAILED ─────────────┴──(cooldown expires)──────────┴─→    (record gone)

- REQUESTED        — persisted BEFORE the provider create call, so a crash
                     mid-launch leaves a record the recovery sweep resolves.
- ALLOCATED        — the provider returned a node id; persisted with it.
- RUNNING          — the node registered with the GCS (joined the cluster).
- IDLE_TRACKED     — no demand; the persisted idle clock is running.
- DRAINING         — the node_drain RPC was issued (persisted FIRST): the
                     GCS schedules around the node and resident train
                     workers grace-checkpoint; termination waits for
                     drain_deadline. One-way: a drained node never returns
                     to service.
- TERMINATING      — persisted BEFORE the provider terminate call; a crash
                     between persist and cloud call re-issues the (idempotent)
                     terminate on restart.
- TERMINATED       — terminal; the record is deleted from the table.
- ALLOCATION_FAILED— the provider create raised (quota/stockout); carries the
                     launch-type cooldown and error so a restarted reconciler
                     keeps suppressing hot relaunches.

The invariant consumers rely on: **every transition is persisted before its
provider side-effect is considered durable** — at any single crash point the
table holds a record from which the converge loop can recover without
double-launching or leaking the node.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

# -- states -----------------------------------------------------------------

REQUESTED = "REQUESTED"
ALLOCATED = "ALLOCATED"
RUNNING = "RUNNING"
IDLE_TRACKED = "IDLE_TRACKED"
DRAINING = "DRAINING"
TERMINATING = "TERMINATING"
TERMINATED = "TERMINATED"
ALLOCATION_FAILED = "ALLOCATION_FAILED"

#: states in which the instance has (or should have) a live provider node
LIVE_STATES = (ALLOCATED, RUNNING, IDLE_TRACKED, DRAINING)
#: states that count toward a node type's min/max capacity. TERMINATING is
#: included: its provider node is still alive until the terminate succeeds,
#: so releasing the slot early would let a cloud-API outage (terminate
#: failing every pass) push provider reality past max_nodes.
COUNTED_STATES = (REQUESTED, ALLOCATED, RUNNING, IDLE_TRACKED, DRAINING,
                  TERMINATING)

_TRANSITIONS: Dict[str, frozenset] = {
    REQUESTED: frozenset({ALLOCATED, ALLOCATION_FAILED, TERMINATED}),
    ALLOCATED: frozenset({RUNNING, IDLE_TRACKED, TERMINATING, TERMINATED}),
    RUNNING: frozenset({IDLE_TRACKED, DRAINING, TERMINATING, TERMINATED}),
    IDLE_TRACKED: frozenset({RUNNING, DRAINING, TERMINATING, TERMINATED}),
    # one-way: a draining node only ever terminates (no return to RUNNING —
    # the GCS-side drain flag is sticky, so the node can't take new work)
    DRAINING: frozenset({TERMINATING, TERMINATED}),
    TERMINATING: frozenset({TERMINATED}),
    ALLOCATION_FAILED: frozenset({TERMINATED}),
    TERMINATED: frozenset(),
}


class InvalidTransition(RuntimeError):
    """A state change the machine does not allow (programming error)."""


def _transition_counter():
    """Instance state-transition counter, fetched registry-aware (the
    monitor process flushes it to the GCS; in-process autoscalers ride the
    driver's flusher)."""
    from ray_tpu.util.metrics import Counter, get_or_create

    return get_or_create(
        Counter, "ray_tpu_autoscaler_instance_transitions_total",
        "autoscaler instance state-machine transitions",
        tag_keys=("node_type", "from_state", "to_state"))


def _count_transition(node_type: str, from_state: str, to_state: str) -> None:
    try:
        _transition_counter().inc(tags={"node_type": node_type,
                                        "from_state": from_state,
                                        "to_state": to_state})
    except Exception:  # noqa: BLE001 — metrics must never fail a transition
        pass


@dataclass
class Instance:
    """One autoscaler-managed node, as persisted in the GCS table.

    All fields are wire-safe primitives; timestamps are wall-clock
    (`time.time()`) because they must stay meaningful across process
    restarts — monotonic clocks don't."""

    instance_id: str
    node_type: str
    state: str = REQUESTED
    node_id: Optional[str] = None       # provider node id, None until ALLOCATED
    launch_time: float = 0.0            # when the provider node was created
    idle_since: Optional[float] = None  # IDLE_TRACKED clock start
    drain_deadline: float = 0.0         # DRAINING: terminate at/after this
    cooldown_until: float = 0.0         # ALLOCATION_FAILED: suppress until
    error: str = ""                     # ALLOCATION_FAILED: provider error
    provider_data: dict = field(default_factory=dict)  # for adopt_node()
    updated_at: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, rec: dict) -> "Instance":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in rec.items() if k in known})


# -- storage backends --------------------------------------------------------


class InstanceStorage:
    """Where instance records durably live. `put` must not return until the
    record is persisted — callers order provider side-effects after it."""

    def put(self, record: dict) -> None:
        raise NotImplementedError

    def delete(self, instance_id: str) -> None:
        raise NotImplementedError

    def list(self) -> List[dict]:
        raise NotImplementedError


class MemoryInstanceStorage(InstanceStorage):
    """Dict-backed storage for unit tests (and as the shared-state fake:
    two managers over one MemoryInstanceStorage model restart)."""

    def __init__(self):
        self.records: Dict[str, dict] = {}

    def put(self, record: dict) -> None:
        self.records[record["instance_id"]] = dict(record)

    def delete(self, instance_id: str) -> None:
        self.records.pop(instance_id, None)

    def list(self) -> List[dict]:
        return [dict(r) for r in self.records.values()]


class GcsInstanceStorage(InstanceStorage):
    """Instance table in the GCS (new `instances` sqlite table, reached via
    the instance_put/instance_delete/instance_list RPCs). `rpc` is a
    synchronous request/reply callable — the autoscaler passes its own."""

    def __init__(self, rpc: Callable[[dict], dict]):
        self._rpc = rpc

    def _call(self, msg: dict) -> dict:
        reply = self._rpc(msg)
        if reply.get("error") or reply.get("ok") is False:
            # the reply IS the durability ack: an error reply (e.g. the
            # GCS sqlite write failed) must surface, or callers would
            # proceed to provider side-effects with nothing persisted
            raise RuntimeError(
                f"{msg['type']} failed at the GCS: "
                f"{reply.get('error') or 'not acknowledged'}")
        return reply

    def put(self, record: dict) -> None:
        self._call({"type": "instance_put", "instance": dict(record)})

    def delete(self, instance_id: str) -> None:
        self._call({"type": "instance_delete", "instance_id": instance_id})

    def list(self) -> List[dict]:
        return list(self._call({"type": "instance_list"})["instances"])


# -- manager -----------------------------------------------------------------


class InstanceManager:
    """Validated, write-through-persisted view of every managed instance."""

    def __init__(self, storage: InstanceStorage):
        self.storage = storage
        self._instances: Dict[str, Instance] = {}
        # guards the in-memory dict: stop() may run teardown concurrently
        # with a wedged reconcile thread, and both read/mutate this view
        # (persistence calls stay OUTSIDE the lock — they do I/O)
        self._lock = threading.Lock()

    # -- lifecycle --------------------------------------------------------

    def load(self) -> List[Instance]:
        """Replace the in-memory view with the persisted table (restart
        rebuild). Returns the loaded instances."""
        loaded = {
            rec["instance_id"]: Instance.from_dict(rec)
            for rec in self.storage.list()
        }
        with self._lock:
            self._instances = loaded
            return list(self._instances.values())

    def create(self, node_type: str, *, now: Optional[float] = None) -> Instance:
        """New REQUESTED instance, persisted before it is returned — the
        caller may only call the provider after this record is durable."""
        now = time.time() if now is None else now
        inst = Instance(instance_id=f"inst-{uuid.uuid4().hex[:12]}",
                        node_type=node_type, state=REQUESTED, updated_at=now)
        self.storage.put(inst.to_dict())
        with self._lock:
            self._instances[inst.instance_id] = inst
        _count_transition(node_type, "(new)", REQUESTED)
        return inst

    def transition(self, inst: Instance, state: str, *,
                   now: Optional[float] = None, **fields) -> Instance:
        """Move `inst` to `state`, updating `fields`, persisting write-through.
        TERMINATED deletes the record (the table tracks live instances).
        The in-memory view only changes after the persist succeeds."""
        with self._lock:
            cur = self._instances.get(inst.instance_id, inst)
        if state not in _TRANSITIONS.get(cur.state, frozenset()):
            raise InvalidTransition(
                f"instance {cur.instance_id} ({cur.node_type}): "
                f"{cur.state} → {state} is not a legal transition")
        updated = Instance.from_dict({**cur.to_dict(), **fields})
        updated.state = state
        updated.updated_at = time.time() if now is None else now
        if state == TERMINATED:
            self.storage.delete(updated.instance_id)
            with self._lock:
                self._instances.pop(updated.instance_id, None)
        else:
            self.storage.put(updated.to_dict())
            with self._lock:
                self._instances[updated.instance_id] = updated
        # counted AFTER the persist: the metric reports durable transitions
        _count_transition(updated.node_type, cur.state, state)
        return updated

    # -- queries ----------------------------------------------------------

    def instances(self, *states: str) -> List[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if states:
            out = [i for i in out if i.state in states]
        return out

    def get(self, instance_id: str) -> Optional[Instance]:
        with self._lock:
            return self._instances.get(instance_id)

    def by_node(self, node_id: str) -> Optional[Instance]:
        with self._lock:
            for inst in self._instances.values():
                if inst.node_id == node_id:
                    return inst
            return None

    def counts(self, states=COUNTED_STATES) -> Dict[str, int]:
        """Per-type instance counts over `states` (capacity accounting)."""
        out: Dict[str, int] = {}
        with self._lock:
            insts = list(self._instances.values())
        for inst in insts:
            if inst.state in states:
                out[inst.node_type] = out.get(inst.node_type, 0) + 1
        return out
