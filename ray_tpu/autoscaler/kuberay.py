"""KubeRay-style operator integration: scale by patching the RayCluster CR.

The operator model (reference: python/ray/autoscaler/v2/instance_manager/
cloud_providers/kuberay/cloud_provider.py + autoscaler/kuberay/): the
autoscaler never creates pods itself — it LAUNCHES by bumping a worker
group's `replicas` and TERMINATES by naming pods in `workersToDelete`
(and decrementing `replicas`); the KubeRay operator reconciles the CR into
actual pods. Instances are observed by listing the cluster's pods.

Built like gce_rest: an injectable transport + token provider so every
request/patch/observe path is testable offline with canned API responses;
production uses the in-cluster service account against
kubernetes.default.svc.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

_SA_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
_SA_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"


class KubeApiError(Exception):
    def __init__(self, status: int, message: str):
        self.status = status
        self.message = message
        super().__init__(f"kubernetes API error {status}: {message}")


def _default_transport(method: str, url: str, headers: Dict[str, str],
                       body: Optional[bytes], timeout: float):
    import ssl

    ctx = ssl.create_default_context(cafile=_SA_CA)
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def serviceaccount_token() -> str:
    with open(_SA_TOKEN) as f:
        return f.read().strip()


class KubeRayApiClient:
    """Minimal k8s API client for the two objects the provider touches:
    the RayCluster custom resource and the cluster's pods."""

    def __init__(self, namespace: str, cluster_name: str, *,
                 api_server: str = "https://kubernetes.default.svc",
                 token_provider: Callable[[], str] = serviceaccount_token,
                 transport=_default_transport, timeout_s: float = 15.0):
        self.namespace = namespace
        self.cluster_name = cluster_name
        self.api_server = api_server.rstrip("/")
        self.token_provider = token_provider
        self.transport = transport
        self.timeout_s = timeout_s

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              content_type: str = "application/json") -> dict:
        headers = {"Authorization": f"Bearer {self.token_provider()}",
                   "Content-Type": content_type,
                   "Accept": "application/json"}
        payload = json.dumps(body).encode() if body is not None else None
        status, data = self.transport(method, self.api_server + path,
                                      headers, payload, self.timeout_s)
        if not 200 <= status < 300:
            try:
                msg = json.loads(data).get("message", "")
            except Exception:
                msg = (data or b"")[:200].decode("utf-8", "replace")
            raise KubeApiError(status, msg)
        return json.loads(data or b"{}")

    def get_cluster(self) -> dict:
        return self._call(
            "GET", f"/apis/ray.io/v1/namespaces/{self.namespace}"
                   f"/rayclusters/{self.cluster_name}")

    def patch_cluster(self, patch: list) -> dict:
        """RFC-6902 JSON-patch on the RayCluster CR — the same mechanism
        the reference uses for replicas/workersToDelete updates."""
        return self._call(
            "PATCH", f"/apis/ray.io/v1/namespaces/{self.namespace}"
                     f"/rayclusters/{self.cluster_name}",
            body=patch, content_type="application/json-patch+json")

    def list_pods(self) -> List[dict]:
        sel = f"ray.io/cluster={self.cluster_name}"
        out = self._call(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods"
                   f"?labelSelector={sel}")
        return out.get("items", [])


def _group_index(cluster: dict, group_name: str) -> int:
    groups = cluster["spec"].get("workerGroupSpecs", [])
    for i, g in enumerate(groups):
        if g.get("groupName") == group_name:
            return i
    raise KeyError(f"worker group {group_name!r} not in RayCluster "
                   f"{[g.get('groupName') for g in groups]}")


class KubeRayNodeProvider(NodeProvider):
    """NodeProvider over the operator contract: launch = replicas+1,
    terminate = workersToDelete + replicas-1, observe = pod list."""

    def __init__(self, api: KubeRayApiClient,
                 default_group: str = "workergroup",
                 launch_ttl_s: float = 600.0):
        self.api = api
        self.default_group = default_group
        self.launch_ttl_s = launch_ttl_s
        self._pod_groups: Dict[str, str] = {}  # pod name → group
        # launch ids whose pod hasn't materialized yet: they must keep
        # appearing in non_terminated_nodes or the reconciler would reap
        # the "instance" and re-bump replicas every pass (runaway scale-up)
        self._pending: Dict[str, tuple] = {}   # launch id → (group, ts)
        self._seen_pods: set = set()
        self._pods_cache: List[dict] = []
        self._pods_fetched_at = float("-inf")

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        group = labels.get("ray.io/group") or node_type or self.default_group
        cluster = self.api.get_cluster()
        i = _group_index(cluster, group)
        spec = cluster["spec"]["workerGroupSpecs"][i]
        replicas = int(spec.get("replicas") or 0)
        self.api.patch_cluster([{
            "op": "replace",
            "path": f"/spec/workerGroupSpecs/{i}/replicas",
            "value": replicas + 1,
        }])
        # the operator chooses the pod name; return a synthetic launch id
        # tracked as pending until a new pod of the group claims it
        # (reference: launch requests are group-granular)
        lid = f"{group}-launch-{replicas + 1}-{int(time.time() * 1e3)}"
        self._pending[lid] = (group, time.monotonic())
        return lid

    def terminate_node(self, node_id: str) -> None:
        """node_id is a POD NAME (as observed); launch ids that never
        materialized terminate by replica decrement alone."""
        self._pending.pop(node_id, None)
        group = self._pod_groups.get(node_id)
        if group is None and "-launch-" in node_id:
            group = node_id.split("-launch-")[0]
        if group is None:
            # unseen pod (e.g. provider restarted): resolve its group from
            # the live pod labels — decrementing a guessed group would
            # shrink the WRONG worker group while the operator respawns
            # the named pod
            self.non_terminated_nodes()
            group = self._pod_groups.get(node_id)
        if group is None:
            raise KubeApiError(
                404, f"cannot terminate {node_id!r}: pod not found in "
                     f"cluster {self.api.cluster_name!r} (group unknown)")
        cluster = self.api.get_cluster()
        i = _group_index(cluster, group)
        spec = cluster["spec"]["workerGroupSpecs"][i]
        replicas = max(0, int(spec.get("replicas") or 0) - 1)
        patch = [{
            "op": "replace",
            "path": f"/spec/workerGroupSpecs/{i}/replicas",
            "value": replicas,
        }]
        if "-launch-" not in node_id:
            existing = (spec.get("scaleStrategy") or {}).get(
                "workersToDelete") or []
            patch.append({
                "op": "replace" if "scaleStrategy" in spec else "add",
                "path": f"/spec/workerGroupSpecs/{i}/scaleStrategy",
                "value": {"workersToDelete": list(existing) + [node_id]},
            })
        self.api.patch_cluster(patch)

    def non_terminated_nodes(self) -> List[str]:
        out = []
        self._pods_cache = self.api.list_pods()
        self._pods_fetched_at = time.monotonic()
        for pod in self._pods_cache:
            meta = pod.get("metadata", {})
            if meta.get("deletionTimestamp"):
                continue
            phase = pod.get("status", {}).get("phase")
            if phase in ("Succeeded", "Failed"):
                continue
            if meta.get("labels", {}).get("ray.io/node-type") == "head":
                continue  # the head is not an autoscaled instance
            name = meta.get("name", "")
            group = meta.get("labels", {}).get("ray.io/group",
                                               self.default_group)
            self._pod_groups[name] = group
            if name not in self._seen_pods:
                self._seen_pods.add(name)
                # a NEW pod claims (retires) the oldest pending launch of
                # its group — the pod name takes over as the instance id
                for lid, (g, ts) in sorted(self._pending.items(),
                                           key=lambda kv: kv[1][1]):
                    if g == group:
                        del self._pending[lid]
                        break
            out.append(name)
        # pending launches count as live instances until they materialize
        # or expire (operator wedged / quota: stop waiting after the TTL
        # so the reconciler can retry)
        now = time.monotonic()
        expired = [lid for lid, v in self._pending.items()
                   if now - v[1] >= self.launch_ttl_s]
        for lid in expired:
            # roll the replica bump back, or every expiry would leak one
            # replica the operator eventually materializes as an extra pod
            try:
                self.terminate_node(lid)
            except Exception:
                self._pending.pop(lid, None)  # give up; retried next pass
        return out + list(self._pending)

    def is_ready(self, node_id: str) -> bool:
        # served from the last pod listing (refreshed at most once per
        # second): per-node API calls would make each reconcile pass
        # O(pods) identical list requests
        now = time.monotonic()
        if now - self._pods_fetched_at > 1.0:
            self._pods_cache = self.api.list_pods()
            self._pods_fetched_at = now
        for pod in self._pods_cache:
            if pod.get("metadata", {}).get("name") != node_id:
                continue
            for cond in pod.get("status", {}).get("conditions", []):
                if cond.get("type") == "Ready":
                    return cond.get("status") == "True"
        return False

    def node_joined(self, node_id: str, gcs_node_ids) -> bool:
        """KubeRay pods self-join with host-id == pod name (the startup
        command passes --host-id $POD_NAME)."""
        return any(str(g) == node_id or str(g).startswith(node_id + "-")
                   for g in gcs_node_ids)
