"""Node providers: how the autoscaler actually creates/terminates nodes.

Reference capability: autoscaler NodeProvider plugins (AWS/GCP/KubeRay/...,
reference: python/ray/autoscaler/node_provider.py + autoscaler/_private/*/
— create_node/terminate_node/non_terminated_nodes) and the v2 cloud
providers (autoscaler/v2/instance_manager/cloud_providers/).

In-tree providers:
- `LocalNodeProvider` spawns node-agent subprocesses joining the live GCS —
  the single-machine analogue of launching a VM (how the reference's fake
  multi-node provider works, autoscaler/_private/fake_multi_node/).
- `FakeFileNodeProvider` keeps its "cloud" in a JSON file outside the
  reconciler process, for crash-restart chaos tests (the mock:// storage
  philosophy applied to nodes), with a SIGKILL fault-injection hook.
- Custom providers subclass NodeProvider (e.g. a GKE TPU-slice provider
  where one "node" is an atomic TPU slice).
"""

from __future__ import annotations

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Interface. Node ids are provider-scoped strings."""

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_ready(self, node_id: str) -> bool:
        """Has the node joined the cluster?"""
        return True

    def node_joined(self, node_id: str, gcs_node_ids) -> bool:
        """Does this provider node correspond to a registered GCS node?
        Providers whose nodes register under different ids override this."""
        return node_id in set(gcs_node_ids)

    def describe_node(self, node_id: str) -> dict:
        """Provider-specific data persisted with the instance record so a
        RESTARTED provider can re-attach to the node (`adopt_node`). Must be
        wire-safe primitives."""
        return {}

    def adopt_node(self, node_id: str, data: dict) -> bool:
        """Re-attach to a node launched by a previous (crashed) incarnation
        of this provider, from its persisted `describe_node` data. Returns
        False if the node is gone — the reconciler reaps its record."""
        return node_id in set(self.non_terminated_nodes())

    def owns_node(self, node_id: str) -> bool:
        """Opt-in gate for the reconciler's leak sweep: True only for nodes
        this autoscaler provably created. The default is False — sweeping a
        node the autoscaler does NOT own (another cluster's, an operator's)
        is far worse than leaking one, so providers must recognize their own
        naming scheme to enable the sweep."""
        return False

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class LocalNodeProvider(NodeProvider):
    """Launches follower node agents as subprocesses against a live GCS.

    An on-disk pid registry (keyed by GCS address) is the local analogue of
    a cloud list API: agents spawned by a CRASHED provider incarnation —
    even one killed between `Popen` and the reconciler's ALLOCATED persist —
    stay visible to `non_terminated_nodes`, so the recovery leak sweep can
    find and terminate them instead of orphaning the process forever."""

    def __init__(self, gcs_address: str, registry_path: str | None = None):
        self.gcs_address = gcs_address
        if registry_path is None:
            # NOT world-writable /tmp: the registry names pids this
            # provider will signal, so any other local user able to write
            # it could direct SIGTERM/SIGKILL at arbitrary processes of
            # ours — keep it in a 0700 per-user directory
            tag = hashlib.sha1(gcs_address.encode()).hexdigest()[:10]
            registry_path = os.path.join(
                _private_state_dir(), f"local_nodes_{tag}.json")
        self.registry_path = registry_path
        self._procs: Dict[str, subprocess.Popen] = {}
        # nodes from a previous provider incarnation, re-attached by
        # (pid, start_time) identity (adopt_node): not our children, so
        # lifecycle is signal/poll-based, and EVERY poll/signal re-verifies
        # the identity — a pid recycled after adoption must never be hit
        self._adopted: Dict[str, tuple] = {}  # node id → (pid, pid_start)
        self._lock = threading.Lock()

    # -- pid registry (best-effort, atomic writes) -------------------------

    def _registry(self) -> dict:
        try:
            with open(self.registry_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _registry_write(self, reg: dict) -> None:
        try:
            _atomic_write_json(self.registry_path, reg)
        except OSError:
            pass  # registry is a best-effort safety net

    def _registry_update(self, node_id: str, ent: Optional[dict]) -> None:
        """Set (or with ent=None, drop) one entry. Caller holds _lock."""
        reg = self._registry()
        if ent is None:
            if node_id not in reg:
                return
            reg.pop(node_id)
        else:
            reg[node_id] = ent
        self._registry_write(reg)

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        host_id = f"as-{node_type}-{uuid.uuid4().hex[:6]}"
        cmd = [sys.executable, "-m", "ray_tpu._private.node_agent",
               "--address", self.gcs_address, "--host-id", host_id]
        if "CPU" in resources:
            cmd += ["--num-cpus", str(resources["CPU"])]
        if "TPU" in resources:
            cmd += ["--num-tpus", str(resources["TPU"])]
        # provisional registry entry BEFORE the spawn: a crash between
        # Popen and the pid write would otherwise orphan the agent with no
        # trace — the restarted incarnation recovers the pid by finding the
        # unique --host-id in /proc cmdlines (_find_agent_pid)
        with self._lock:
            self._registry_update(host_id, {"pid": None,
                                            "created_at": time.time()})
        p = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[host_id] = p
            self._registry_update(host_id, {
                "pid": p.pid, "pid_start": _pid_start_time(p.pid)})
        return host_id

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            p = self._procs.pop(node_id, None)
            pid = None
            adopted = self._adopted.pop(node_id, None)
            if adopted is not None:
                apid, astart = adopted
                if _pid_identity_ok(apid, astart):
                    pid = apid
            elif p is None:
                # registry-only orphan (spawned by a crashed incarnation):
                # kill by registered pid, guarding against pid reuse; a
                # provisional (pid-less) entry resolves via /proc cmdlines
                ent = self._registry().get(node_id) or {}
                if ent.get("pid") is None:
                    pid = _find_agent_pid(node_id)
                else:
                    rpid = int(ent.get("pid") or 0)
                    if rpid > 0 and _pid_identity_ok(rpid,
                                                     ent.get("pid_start")):
                        pid = rpid
        if p is not None:
            p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)
        elif pid is not None:
            # not our child — signal and poll for exit
            try:
                os.kill(pid, signal.SIGTERM)
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    if not _pid_alive(pid):
                        break
                    time.sleep(0.05)
                else:
                    os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        with self._lock:
            self._registry_update(node_id, None)

    def non_terminated_nodes(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            dead: List[str] = []
            # reap exited agents as we list: poll() collects the child's
            # exit status (no zombie) and the entry is dropped so _procs
            # can't accumulate dead Popen handles forever
            for nid, p in list(self._procs.items()):
                if p.poll() is None:
                    out.append(nid)
                else:
                    self._procs.pop(nid)
                    dead.append(nid)
            for nid, (pid, start) in list(self._adopted.items()):
                if _pid_alive(pid) and _pid_identity_ok(pid, start):
                    out.append(nid)
                else:
                    self._adopted.pop(nid)
                    dead.append(nid)
            # registry-only entries: a crashed incarnation's agents, still
            # running (pid + start time match) — visible so the reconciler's
            # sweep can claim or terminate them. Dead/stale entries and the
            # reaps above fold into ONE registry rewrite per listing.
            seen = set(out)
            reg = self._registry()
            changed = False
            for nid in dead:
                changed = bool(reg.pop(nid, None)) or changed
            now = time.time()
            for nid, ent in list(reg.items()):
                if nid in seen:
                    continue
                if ent.get("pid") is None:
                    # provisional entry: the spawner died between Popen and
                    # the pid write — recover the pid from the agent's own
                    # cmdline, or prune once it's clearly not coming
                    found = _find_agent_pid(nid)
                    if found is not None:
                        ent["pid"] = found
                        ent["pid_start"] = _pid_start_time(found)
                        changed = True
                        out.append(nid)
                    elif now - float(ent.get("created_at") or 0) > 10.0:
                        reg.pop(nid)
                        changed = True
                    continue
                pid = int(ent.get("pid") or 0)
                if (pid > 0 and _pid_alive(pid)
                        and _pid_identity_ok(pid, ent.get("pid_start"))):
                    out.append(nid)
                else:
                    reg.pop(nid)
                    changed = True
            if changed:
                self._registry_write(reg)
        return out

    def describe_node(self, node_id: str) -> dict:
        with self._lock:
            p = self._procs.get(node_id)
            if p is not None:
                # pid_start disambiguates pid reuse: a recycled pid belongs
                # to a DIFFERENT process even though os.kill(pid, 0) says
                # "alive"
                return {"pid": p.pid, "pid_start": _pid_start_time(p.pid)}
            adopted = self._adopted.get(node_id)
        if adopted is None:
            return {}
        return {"pid": adopted[0], "pid_start": adopted[1]}

    def adopt_node(self, node_id: str, data: dict) -> bool:
        pid = int(data.get("pid") or 0)
        if pid <= 0 or not _pid_alive(pid):
            return False
        start = data.get("pid_start")
        if not _pid_identity_ok(pid, start):
            # recycled pid, or identity unverifiable (no /proc): adopting
            # — and later SIGTERMing — could hit an unrelated process;
            # treat the node as gone instead
            return False
        with self._lock:
            self._adopted[node_id] = (pid, start)
        return True

    def owns_node(self, node_id: str) -> bool:
        return node_id.startswith("as-")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass  # e.g. EPERM: it exists
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            # state is the first field after the comm's closing ')';
            # a zombie has exited — only its parent's reap is pending
            if f.read().rsplit(b")", 1)[1].split()[0] == b"Z":
                return False
    except (OSError, IndexError):
        pass
    return True


def _pid_start_time(pid: int):
    """Kernel start time (clock ticks since boot) from /proc/<pid>/stat,
    or None where /proc isn't available. (pid, start_time) identifies a
    process uniquely for the lifetime of the boot."""
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        # comm (field 2) may contain spaces/parens: parse past the LAST ')'
        # — starttime is overall field 22, i.e. index 19 of the remainder
        return int(data.rsplit(b")", 1)[1].split()[19])
    except (OSError, ValueError, IndexError):
        return None


def _pid_identity_ok(pid: int, want_start) -> bool:
    """True only when the process's identity is POSITIVELY verified: a
    recycled pid must never be signalled, so `None` on either side (e.g.
    no /proc on this platform) means unverifiable → not ours."""
    got = _pid_start_time(pid)
    return got is not None and got == want_start


def _private_state_dir() -> str:
    """A 0700 per-user directory for provider state (the pid registry)."""
    base = os.environ.get("XDG_CACHE_HOME") or os.path.expanduser("~/.cache")
    path = os.path.join(base, "ray_tpu")
    try:
        os.makedirs(path, mode=0o700, exist_ok=True)
        return path
    except OSError:
        pass
    path = os.path.join(tempfile.gettempdir(), f"ray_tpu-{os.getuid()}")
    os.makedirs(path, mode=0o700, exist_ok=True)
    return path


def _find_agent_pid(host_id: str):
    """Recover a lost agent pid by its unique --host-id argv entry in /proc
    cmdlines (the crash window between Popen and the registry pid write)."""
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return None
    for p in pids:
        try:
            with open(f"/proc/{p}/cmdline", "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if (host_id.encode() in argv
                and b"ray_tpu._private.node_agent" in argv):
            return int(p)
    return None


def _atomic_write_json(path: str, obj) -> None:
    """tmp + fsync + rename: a crash mid-write leaves the old content, not
    a torn file (both the pid registry and the fake cloud rely on it)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class FakeFileNodeProvider(NodeProvider):
    """Fake provider whose "cloud" is a JSON state file OUTSIDE the
    reconciler process: a SIGKILLed monitor's nodes persist on disk and a
    restarted provider instance sees the exact same ground truth — which is
    what makes crash-restart chaos tests real (tests/test_autoscaler_chaos.py).

    State file: {"nodes": {node_id: {...}}, "creates": N} — `creates` is the
    lifetime create_node count, letting tests assert "no double-launch".

    Fault injection: `die_after_create=N` SIGKILLs the calling process right
    after the Nth create_node commits the node to the file but BEFORE
    returning — the reconciler is killed exactly between the provider
    side-effect and its ALLOCATED persist. Fires once per state file (a
    `<path>.died` marker survives the restart)."""

    def __init__(self, path: str, die_after_create: int = 0):
        self.path = path
        self.die_after_create = int(die_after_create)
        self._lock = threading.Lock()

    # -- file-backed "cloud" ----------------------------------------------

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"nodes": {}, "creates": 0}

    def _save(self, state: dict) -> None:
        _atomic_write_json(self.path, state)

    # -- NodeProvider surface ---------------------------------------------

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        with self._lock:
            state = self._load()
            state["creates"] = int(state.get("creates", 0)) + 1
            nid = f"ff-{node_type}-{state['creates']}-{uuid.uuid4().hex[:4]}"
            state["nodes"][nid] = {"node_type": node_type,
                                   "resources": dict(resources),
                                   "created_at": time.time()}
            self._save(state)
            if (self.die_after_create
                    and state["creates"] >= self.die_after_create
                    and not os.path.exists(self.path + ".died")):
                with open(self.path + ".died", "w") as f:
                    f.write(str(os.getpid()))
                os.kill(os.getpid(), signal.SIGKILL)
        return nid

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            state = self._load()
            state["nodes"].pop(node_id, None)
            self._save(state)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._load()["nodes"])

    def describe_node(self, node_id: str) -> dict:
        return {"path": self.path}

    def owns_node(self, node_id: str) -> bool:
        return node_id.startswith("ff-")
