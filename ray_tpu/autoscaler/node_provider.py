"""Node providers: how the autoscaler actually creates/terminates nodes.

Reference capability: autoscaler NodeProvider plugins (AWS/GCP/KubeRay/...,
reference: python/ray/autoscaler/node_provider.py + autoscaler/_private/*/
— create_node/terminate_node/non_terminated_nodes) and the v2 cloud
providers (autoscaler/v2/instance_manager/cloud_providers/).

Two in-tree providers:
- `LocalNodeProvider` spawns node-agent subprocesses joining the live GCS —
  the single-machine analogue of launching a VM (how the reference's fake
  multi-node provider works, autoscaler/_private/fake_multi_node/).
- Custom providers subclass NodeProvider (e.g. a GKE TPU-slice provider
  where one "node" is an atomic TPU slice).
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Interface. Node ids are provider-scoped strings."""

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def is_ready(self, node_id: str) -> bool:
        """Has the node joined the cluster?"""
        return True

    def node_joined(self, node_id: str, gcs_node_ids) -> bool:
        """Does this provider node correspond to a registered GCS node?
        Providers whose nodes register under different ids override this."""
        return node_id in set(gcs_node_ids)

    def shutdown(self) -> None:
        for nid in list(self.non_terminated_nodes()):
            self.terminate_node(nid)


class LocalNodeProvider(NodeProvider):
    """Launches follower node agents as subprocesses against a live GCS."""

    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: str, resources: Dict[str, float],
                    labels: Dict[str, str]) -> str:
        host_id = f"as-{node_type}-{uuid.uuid4().hex[:6]}"
        cmd = [sys.executable, "-m", "ray_tpu._private.node_agent",
               "--address", self.gcs_address, "--host-id", host_id]
        if "CPU" in resources:
            cmd += ["--num-cpus", str(resources["CPU"])]
        if "TPU" in resources:
            cmd += ["--num-tpus", str(resources["TPU"])]
        p = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
        with self._lock:
            self._procs[host_id] = p
        return host_id

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            p = self._procs.pop(node_id, None)
        if p is not None:
            p.terminate()
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return [nid for nid, p in self._procs.items() if p.poll() is None]
