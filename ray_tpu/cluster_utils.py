"""In-process multi-node cluster harness for tests.

Virtual nodes are resource partitions registered with the GCS; each gets its
own worker subprocesses tagged with its node id, so scheduling policies,
placement-group strategies, and node-failure paths are exercised for real on
one machine.

(reference: python/ray/cluster_utils.py:135 — Cluster/add_node run real
GCS/raylet processes per "node" on one machine; that harness is how the
reference tests multi-node without a cluster, SURVEY.md §4.2.)
"""

from __future__ import annotations

import itertools

import ray_tpu
from ray_tpu._private import api as _api


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._counter = itertools.count(1)
        self.head_args = head_node_args or {}
        self.node_ids: list[str] = []
        if initialize_head:
            ray_tpu.init(**self.head_args)
            self.node_ids.append("node-0")

    def add_node(self, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: dict | None = None, labels: dict | None = None,
                 node_id: str | None = None) -> str:
        node_id = node_id or f"node-{next(self._counter)}"
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if resources:
            res.update({k: float(v) for k, v in resources.items()})
        _api._get_worker().add_node(node_id, res, labels)
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: str):
        _api._get_worker().remove_node(node_id)
        if node_id in self.node_ids:
            self.node_ids.remove(node_id)

    def shutdown(self):
        ray_tpu.shutdown()
