"""In-process multi-node cluster harness for tests.

Virtual nodes are resource partitions registered with the GCS; each gets its
own worker subprocesses tagged with its node id, so scheduling policies,
placement-group strategies, and node-failure paths are exercised for real on
one machine.

(reference: python/ray/cluster_utils.py:135 — Cluster/add_node run real
GCS/raylet processes per "node" on one machine; that harness is how the
reference tests multi-node without a cluster, SURVEY.md §4.2.)
"""

from __future__ import annotations

import itertools
import os

import ray_tpu
from ray_tpu._private import api as _api


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._counter = itertools.count(1)
        self.head_args = head_node_args or {}
        self.node_ids: list[str] = []
        self.host_ids: list[str] = []
        self._agents: dict = {}
        if initialize_head:
            ray_tpu.init(**self.head_args)
            self.node_ids.append("node-0")

    def add_node(self, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 resources: dict | None = None, labels: dict | None = None,
                 node_id: str | None = None) -> str:
        node_id = node_id or f"node-{next(self._counter)}"
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        if resources:
            res.update({k: float(v) for k, v in resources.items()})
        _api._get_worker().add_node(node_id, res, labels)
        self.node_ids.append(node_id)
        return node_id

    def remove_node(self, node_id: str):
        _api._get_worker().remove_node(node_id)
        if node_id in self.node_ids:
            self.node_ids.remove(node_id)

    def add_host(self, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
                 host_id: str | None = None, wait: bool = True,
                 env: dict | None = None) -> str:
        """Start a follower-HOST process: a real node agent subprocess with
        its own shm namespace and worker pool, joined over TCP — the closest
        one machine gets to a second machine. (reference: cluster_utils
        add_node runs real raylet processes per node, SURVEY.md §4.2.)"""
        import subprocess
        import sys
        import time

        host_id = host_id or f"host-{next(self._counter)}"
        node = _api._node
        assert node is not None, "head must be initialized first"
        args = [sys.executable, "-m", "ray_tpu._private.node_agent",
                "--address", node.address, "--host-id", host_id,
                "--num-cpus", str(num_cpus)]
        if num_tpus:
            args += ["--num-tpus", str(num_tpus)]
        child_env = dict(os.environ)
        child_env.pop("RAY_TPU_ADDRESS", None)  # agent dials --address
        if env:
            child_env.update(env)
        log = open(os.path.join(node.session_dir, "logs", f"agent-{host_id}.log"), "ab")
        try:
            p = subprocess.Popen(args, env=child_env, stdout=log,
                                 stderr=subprocess.STDOUT, cwd=os.getcwd())
        finally:
            log.close()
        self._agents[host_id] = p
        if wait:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                nodes = _api._get_worker().list_nodes()
                if any(n["node_id"] == host_id and n["alive"] for n in nodes):
                    break
                time.sleep(0.05)
            else:
                raise TimeoutError(f"host {host_id} did not register")
        self.host_ids.append(host_id)
        return host_id

    def remove_host(self, host_id: str):
        """Kill the agent process; the GCS notices the dead connection and
        fails the host's nodes/workers (host-failure path)."""
        p = self._agents.pop(host_id, None)
        if p is not None:
            p.kill()
            p.wait(timeout=10)
        if host_id in self.host_ids:
            self.host_ids.remove(host_id)

    def shutdown(self):
        for host_id in list(self._agents):
            self.remove_host(host_id)
        ray_tpu.shutdown()
