"""Cross-language task calls: invoke REGISTERED native-worker functions.

(reference: ray.cross_language / the C++ worker API — tasks target
functions by NAME so any driver can call into a C++ worker; args/results
are restricted to language-neutral values. Here that wire format is JSON
frames on the shared control plane; `cpp/cpp_worker.cc` is the worker.)

    h = ray_tpu.cpp_function("add")
    ray_tpu.get(h.remote(2, 3))  # -> 5  (computed in C++)

`start_cpp_worker()` builds (g++, cached) and launches the bundled worker
binary against the current session — production deployments run the binary
themselves, linking their own function registrations.
"""

from __future__ import annotations

import os
import subprocess
import threading

def _check_jsonable(v, path="arg"):
    """Deep-validate a cross-language value: JSON types only, finite
    floats, string keys. A nested reject must fail HERE at call time —
    inside the GCS dispatch flush it would abort the whole send pass."""
    if v is None or isinstance(v, (bool, str)):
        return
    if isinstance(v, int):
        return
    if isinstance(v, float):
        import math

        if not math.isfinite(v):
            raise TypeError(f"{path}: non-finite float {v!r} is not "
                            "JSON-encodable")
        return
    if isinstance(v, (list, tuple)):
        for i, x in enumerate(v):
            _check_jsonable(x, f"{path}[{i}]")
        return
    if isinstance(v, dict):
        for k, x in v.items():
            if not isinstance(k, str):
                raise TypeError(f"{path}: dict keys must be str, got "
                                f"{type(k).__name__}")
            _check_jsonable(x, f"{path}[{k!r}]")
        return
    raise TypeError(
        f"{path}: cross-language args must be JSON-encodable; got "
        f"{type(v).__name__} (wrap arrays as lists)")

_CPP_DIR = os.path.join(os.path.dirname(__file__), "..", "cpp")
_build_lock = threading.Lock()


class CppFunction:
    """Handle to a named function registered in cross-language workers."""

    def __init__(self, name: str, *, num_cpus: float = 1.0):
        self.name = name
        self.num_cpus = num_cpus

    def remote(self, *args):
        from ray_tpu._private.api import _get_worker

        for i, a in enumerate(args):
            _check_jsonable(a, f"args[{i}]")
        return _get_worker().submit_cross_lang_task(
            self.name, list(args), lang="cpp",
            resources={"CPU": float(self.num_cpus)})

    def options(self, *, num_cpus: float | None = None) -> "CppFunction":
        return CppFunction(self.name,
                           num_cpus=self.num_cpus if num_cpus is None
                           else num_cpus)


def cpp_function(name: str) -> CppFunction:
    return CppFunction(name)


def ensure_cpp_worker_binary() -> str:
    """Build cpp/cpp_worker.cc once (same auto-build pattern as the native
    store); returns the binary path."""
    build = os.path.join(_CPP_DIR, "build")
    binary = os.path.join(build, "cpp_worker")
    src = os.path.join(_CPP_DIR, "cpp_worker.cc")
    with _build_lock:
        if (os.path.exists(binary)
                and os.path.getmtime(binary) >= os.path.getmtime(src)):
            return binary
        os.makedirs(build, exist_ok=True)
        tmp = binary + f".tmp{os.getpid()}"
        subprocess.run(["g++", "-O2", "-std=c++17", "-o", tmp, src],
                       check=True, capture_output=True, text=True)
        os.replace(tmp, binary)
    return binary


def start_cpp_worker(address: str | None = None) -> subprocess.Popen:
    """Launch the bundled C++ worker joined to the current session (or an
    explicit GCS host:port address)."""
    if address is None:
        import ray_tpu._private.api as _api

        node = _api._node
        if node is None:
            raise RuntimeError("ray_tpu.init() first (or pass address=)")
        address = node.address
    binary = ensure_cpp_worker_binary()
    return subprocess.Popen([binary, "--address", address])
