"""Lazy DAG API + compiled execution.

(reference: python/ray/dag/ — DAGNode/InputNode/MultiOutputNode
(dag_node.py, input_node.py, output_node.py), .bind() builders on tasks and
actor methods, experimental_compile → CompiledDAG
(compiled_dag_node.py:805).)
"""

from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    CompiledDAG,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "ClassMethodNode",
    "CompiledDAG",
    "DAGNode",
    "FunctionNode",
    "InputNode",
    "MultiOutputNode",
]
