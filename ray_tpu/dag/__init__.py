"""Lazy DAG API + compiled execution.

(reference: python/ray/dag/ — DAGNode/InputNode/MultiOutputNode
(dag_node.py, input_node.py, output_node.py), .bind() builders on tasks and
actor methods, experimental_compile → CompiledDAG
(compiled_dag_node.py:805). The compiled form runs on the channel execution
plane when eligible: per-actor exec loops over mutable-shm channels,
channel_execution.py.)
"""

from ray_tpu.dag.channel_execution import ChannelDAGFuture, ChannelExecutor
from ray_tpu.dag.dag_node import (
    AwaitableDAGFuture,
    ClassMethodNode,
    CompiledDAG,
    DAGFuture,
    DAGNode,
    FunctionNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "AwaitableDAGFuture",
    "ChannelDAGFuture",
    "ChannelExecutor",
    "ClassMethodNode",
    "CompiledDAG",
    "DAGFuture",
    "DAGNode",
    "FunctionNode",
    "InputNode",
    "MultiOutputNode",
]
